"""Checkpoint save/restore.

Re-design of the reference's checkpoint subsystem (ref:
benchmark_cnn.py:905-924 load_checkpoint, :2076-2082 Saver over
savable_variables, :2304-2309 periodic save, :2374-2378 final save;
variable_mgr.py:358-365 v0-only savable variables in replicated mode).

Design: the per-replica stacked TrainState saves its replica-0 slice --
the exact analog of the reference's "save only the v0 copy" rule, and the
reason checkpoints interoperate across every variable_update mode (the
distributed_replicated name-stripping of variable_mgr.py:807-828 is
unnecessary: the on-disk layout is mode-invariant by construction).

Format: flax msgpack of host numpy trees, one file per step
(``model.ckpt-<step>.msgpack``) plus a ``checkpoint`` index file naming
the latest -- relative paths only, so directories are relocatable
(ref test: benchmark_cnn_test.py:688 testMoveTrainDir).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization


class CheckpointNotFoundException(Exception):
  """(ref: benchmark_cnn.py:905-910)"""


_CKPT_RE = re.compile(r"model\.ckpt-(\d+)\.msgpack$")


def _index_path(train_dir: str) -> str:
  return os.path.join(train_dir, "checkpoint")


def is_chief() -> bool:
  """Checkpoint writes are chief-only in multi-host runs (ref:
  Supervisor is_chief + chief-only Saver, benchmark_cnn.py:2039-2082).
  Replica 0 lives on process 0's first device, so the chief can always
  address the slice it saves."""
  return jax.process_index() == 0


def _replica0_local(x):
  """Replica-0 slice read from LOCAL shards only.

  ``np.asarray(x[0])`` on a multi-process sharded array dispatches a
  global slice computation that every process must join -- on the chief
  alone it deadlocks (observed: restart-resize checkpoint hung the
  2-process test). Replica 0 is addressable on the chief, so read the
  shard whose index range covers row 0 directly."""
  shards = getattr(x, "addressable_shards", None)
  if shards and getattr(x, "ndim", 0) >= 1:
    for s in shards:
      idx = s.index
      sl = idx[0] if idx else slice(None)
      start = sl.start or 0
      if start == 0:
        return np.asarray(jax.device_get(s.data))[0]
  return np.asarray(x[0])


def savable_state(state, sharded_opt_state: bool = False,
                  input_incarnation: int = 0,
                  sharded_params: bool = False) -> dict:
  """Host-side, mode-invariant snapshot: replica-0 slice of the stacked
  arrays + replicated scalars (ref: variable_mgr savable_variables).

  ``sharded_opt_state=True`` (--shard_optimizer_state runs): the
  opt_state rows are per-device 1/n SHARDS, not copies, so the v0-only
  rule would drop (n-1)/n of the state -- the FULL stacked ``(n, k)``
  arrays are saved instead and the snapshot is marked with
  ``opt_state_layout`` so restore_state re-shards rather than
  broadcasts. Model variables (params/batch_stats) stay v0-sliced and
  mode-invariant, so eval / restore_opt_state=False interop across
  modes is preserved; validation.py keeps sharded runs single-process,
  which is what makes every row chief-addressable here.

  ``sharded_params=True`` (--shard_params): the PARAMS rows are shards
  too (the FSDP steady state), so they follow the same full-stack rule
  and the snapshot carries ``params_layout`` -- an FSDP checkpoint is
  NOT v0-readable and only resumes --shard_params runs (restore_state
  rejects cross-layout restores loudly; batch_stats stay v0-sliced,
  they never shard)."""
  slice0 = lambda t: jax.tree.map(_replica0_local, t)
  snap = {
      "step": int(state.step),
      "params": (jax.tree.map(np.asarray, state.params)
                 if sharded_params else slice0(state.params)),
      "opt_state": (jax.tree.map(np.asarray, state.opt_state)
                    if sharded_opt_state else slice0(state.opt_state)),
      "batch_stats": slice0(state.batch_stats),
      "loss_scale": float(state.loss_scale),
      "loss_scale_normal_steps": int(state.loss_scale_normal_steps),
  }
  if sharded_opt_state:
    snap["opt_state_layout"] = "sharded"
  if sharded_params:
    snap["params_layout"] = "sharded"
  if input_incarnation:
    # The input-stream incarnation the RESUMED run must reopen at
    # (benchmark._open_input folds the data rng by it after elastic
    # reshapes): without this, a preemption after a resize would
    # silently reset the rejoined run to stream 0.
    snap["input_incarnation"] = int(input_incarnation)
  return snap


def save_checkpoint(train_dir: str, state, max_to_keep: int = 5,
                    sharded_opt_state: bool = False,
                    input_incarnation: int = 0,
                    sharded_params: bool = False) -> str:
  """Write a checkpoint; prune beyond ``max_to_keep``
  (ref: --max_ckpts_to_keep, benchmark_cnn.py:606-608). No-op on
  non-chief processes."""
  if not is_chief():
    return ""
  # rank0-owns: the chief is the one checkpoint writer (ref
  # --max_ckpts_to_keep semantics); non-chief ranks returned above, and
  # restore() on every rank reads what this one rank wrote.
  os.makedirs(train_dir, exist_ok=True)
  snap = savable_state(state, sharded_opt_state=sharded_opt_state,
                       input_incarnation=input_incarnation,
                       sharded_params=sharded_params)
  step = snap["step"]
  fname = f"model.ckpt-{step}.msgpack"
  path = os.path.join(train_dir, fname)
  # to_state_dict flattens namedtuple optimizer states into plain dicts
  # so the file stays a self-describing msgpack map.
  with open(path + ".tmp", "wb") as f:
    f.write(serialization.msgpack_serialize(
        serialization.to_state_dict(snap)))
  os.replace(path + ".tmp", path)
  with open(_index_path(train_dir) + ".tmp", "w") as f:
    json.dump({"latest": fname}, f)
  os.replace(_index_path(train_dir) + ".tmp", _index_path(train_dir))
  _prune(train_dir, max_to_keep)
  return path


def _prune(train_dir: str, max_to_keep: int) -> None:
  if not max_to_keep:
    return
  ckpts = all_checkpoints(train_dir)
  for step, fname in ckpts[:-max_to_keep]:
    try:
      os.remove(os.path.join(train_dir, fname))
    except OSError:
      pass


def all_checkpoints(train_dir: str):
  """Sorted (step, filename) list."""
  out = []
  try:
    for fname in os.listdir(train_dir):
      m = _CKPT_RE.match(fname)
      if m:
        out.append((int(m.group(1)), fname))
  except FileNotFoundError:
    pass
  return sorted(out)


def readable_checkpoint(path: str) -> bool:
  """Whether ``path`` holds a complete, parseable snapshot. Writes are
  atomic (tmp + os.replace in save_checkpoint), so a torn file can only
  come from outside the save protocol -- a copy killed mid-transfer, a
  truncated disk, an injected corrupt_ckpt fault (faults.py) -- and the
  msgpack parse is the cheap whole-file integrity check."""
  try:
    load_checkpoint(path)
    return True
  except Exception:
    return False


def _candidates(train_dir: str):
  """(step, fname) candidates newest-first: the index target first (when
  valid), then the directory scan -- a missing/stale index must not
  orphan valid checkpoints, and a corrupt index target must not mask
  the older snapshots behind it."""
  candidates = []
  try:
    with open(_index_path(train_dir)) as f:
      fname = json.load(f)["latest"]
    m = _CKPT_RE.match(fname)
    if m and os.path.exists(os.path.join(train_dir, fname)):
      candidates.append((int(m.group(1)), fname))
  except (FileNotFoundError, json.JSONDecodeError, KeyError):
    pass
  for step, fname in reversed(all_checkpoints(train_dir)):
    if (step, fname) not in candidates:
      candidates.append((step, fname))
  candidates.sort(reverse=True)
  return candidates


def latest_checkpoint(train_dir: str) -> Tuple[str, int]:
  """Resolve the newest checkpoint path; the step is parsed from the
  filename (ref: benchmark_cnn.py:911-924). Cheap (no file parse):
  pollers call this every staleness interval. Restore paths that must
  survive a torn file go through :func:`load_latest_checkpoint`, which
  parses exactly once and skips corrupt files."""
  candidates = _candidates(train_dir)
  if not candidates:
    raise CheckpointNotFoundException(
        f"No checkpoint found in {train_dir}")
  step, fname = candidates[0]
  return os.path.join(train_dir, fname), step


def load_latest_checkpoint(train_dir: str):
  """(snapshot, path, step) of the newest READABLE checkpoint.
  Torn/corrupt files are skipped with a logged warning (a partial file
  -- a copy killed mid-transfer, an injected corrupt_ckpt fault; the
  save protocol itself is atomic tmp + os.replace -- must never poison
  resume: the run falls back to the previous snapshot). The msgpack
  parse doubles as the whole-file integrity check and the snapshot is
  parsed exactly ONCE (callers restore from the returned dict).
  Raises CheckpointNotFoundException."""
  from kf_benchmarks_tpu.utils import log as log_util
  candidates = _candidates(train_dir)
  skipped = 0
  for step, fname in candidates:
    path = os.path.join(train_dir, fname)
    try:
      return load_checkpoint(path), path, step
    except Exception:
      skipped += 1
      log_util.log_fn(
          f"Warning: skipping torn/corrupt checkpoint {fname} "
          "(unparseable msgpack); resuming from the previous snapshot")
  if not candidates:
    raise CheckpointNotFoundException(
        f"No checkpoint found in {train_dir}")
  raise CheckpointNotFoundException(
      f"No readable checkpoint in {train_dir} "
      f"({skipped} corrupt file(s) skipped)")


def load_checkpoint(path: str) -> dict:
  with open(path, "rb") as f:
    return serialization.msgpack_restore(f.read())


def _reseed_staged(buffers, params):
  """Point the staged-reads buffer at the (new) live params: after any
  restore, the first forward must read the restored weights, not the
  fresh-init ones the buffer was created from (--staged_vars; the
  StagingArea warmup refill analog, variable_mgr_util.py:236-310)."""
  if isinstance(buffers, dict) and "staged_params" in buffers:
    buffers = dict(buffers)
    buffers["staged_params"] = params
  return buffers


def restore_state(state, snapshot: dict, restore_opt_state: bool = True,
                  sharded_opt_state: bool = False,
                  sharded_params: bool = False):
  """Rebuild a stacked device TrainState from a host snapshot: replica-0
  values are broadcast to every replica (the restore-side analog of the
  reference's post-init v0->v* copy, variable_mgr.py:342-356).

  ``restore_opt_state=False`` restores model variables only -- the eval
  path's semantic (the reference's eval graph holds no optimizer slots,
  so its Saver restore never touches them, ref benchmark_cnn.py:
  1829-1862): an eval process must be able to read a checkpoint written
  under ANY optimizer, not just the one its own flags happen to default
  to.

  Snapshots marked ``opt_state_layout == 'sharded'`` carry the FULL
  stacked shard arrays (see savable_state); they restore only into a
  state whose opt_state is also sharded -- a sharded<->replicated
  layout mismatch raises in either direction (re-slicing 1/n flat
  shards into the other layout silently would corrupt the optimizer
  state). A sharded snapshot written at a DIFFERENT shard count
  re-slices onto the live topology (``_reshard``): both layouts are the
  zero-padded row-major flatten of the same full state, so the rescale
  is exact -- the cross-mesh elastic-resume leg (ROADMAP item 3),
  replacing the round-11 cross-layout rejection."""
  snap_sharded = snapshot.get("opt_state_layout") == "sharded"
  if restore_opt_state and snap_sharded != sharded_opt_state:
    raise ValueError(
        f"checkpoint opt_state layout is "
        f"{'sharded' if snap_sharded else 'replicated'} but the run's "
        f"is {'sharded' if sharded_opt_state else 'replicated'}: "
        "--shard_optimizer_state checkpoints only resume sharded runs "
        "of the same topology (pass restore_opt_state=False to warm-"
        "start model variables only)")
  snap_fsdp = snapshot.get("params_layout") == "sharded"
  if snap_fsdp != sharded_params:
    if snap_fsdp and not sharded_params and not restore_opt_state:
      # Model-variables-only restore (the EVAL path's semantic; the
      # eval graph never trains, so validation's --shard_params
      # training-only rule keeps eval runs replicated by
      # construction): de-shard the saved stacks host-side against
      # the live full-shape template -- the flat row-major addressing
      # is exact (ops/sharded.py fsdp_stacked_shards), so an FSDP
      # checkpoint stays eval-readable like every other layout.
      full = _deshard_params(state.params, snapshot["params"])
      return state.replace(
          step=jnp.asarray(snapshot["step"], jnp.int32),
          params=_restack(state.params, full),
          batch_stats=_restack(state.batch_stats,
                               snapshot["batch_stats"]),
          loss_scale=jnp.asarray(snapshot["loss_scale"], jnp.float32),
          loss_scale_normal_steps=jnp.asarray(
              snapshot["loss_scale_normal_steps"], jnp.int32),
          buffers=_reseed_staged(
              state.buffers, _restack(state.params, full)))
    # TRAIN resumes across layouts are rejected in both directions: an
    # FSDP snapshot's rows are 1/n flat shards a replicated run would
    # silently broadcast as whole tensors, and vice versa.
    raise ValueError(
        f"checkpoint params layout is "
        f"{'sharded (FSDP)' if snap_fsdp else 'replicated'} but the "
        f"run's is {'sharded (FSDP)' if sharded_params else 'replicated'}"
        ": --shard_params checkpoints only resume --shard_params runs "
        "(and vice versa) -- re-run with the matching flag (eval "
        "restores, restore_opt_state=False, de-shard automatically)")
  if sharded_params:
    params = _reshard(state.params, snapshot["params"])
  else:
    params = _restack(state.params, snapshot["params"])
  if restore_opt_state:
    if snap_sharded:
      # Reshard cost on the run-trace checkpoint lane (tracing.py
      # no-op sink without a session): the re-address of the (n, k)
      # shard stack is a distinct, size-dependent slice of an elastic
      # seam's wall that the timeline should show next to the save and
      # the re-jit, not blended into "restore".
      from kf_benchmarks_tpu import tracing
      with tracing.active().span("checkpoint", "reshard_opt_state"):
        new_opt = _reshard(state.opt_state, snapshot["opt_state"])
    else:
      new_opt = _restack(state.opt_state, snapshot["opt_state"])
  else:
    new_opt = state.opt_state
  return state.replace(
      step=jnp.asarray(snapshot["step"], jnp.int32),
      params=params,
      opt_state=new_opt,
      batch_stats=_restack(state.batch_stats, snapshot["batch_stats"]),
      loss_scale=jnp.asarray(snapshot["loss_scale"], jnp.float32),
      loss_scale_normal_steps=jnp.asarray(
          snapshot["loss_scale_normal_steps"], jnp.int32),
      buffers=_reseed_staged(state.buffers, params),
  )


def _lookup_path(tree, path):
  """Navigate a nested state-dict by a jax key path; None if absent."""
  node = tree
  for p in path:
    key = getattr(p, "key", None)
    if key is None:
      key = getattr(p, "idx", None)
    if isinstance(node, dict) and str(key) in node:
      node = node[str(key)]
    else:
      return None
  return node


def restore_backbone(state, path: str):
  """Warm-start from a backbone checkpoint: restore the intersection of
  the checkpoint's params/batch_stats with the live state, matched by
  variable path and shape (ref: --backbone_model_path,
  benchmark_cnn.py:2204-2205; models/model.py:170-190
  add_backbone_saver/load_backbone_model -- the reference maps TF
  variable names through a custom Saver; here module paths are the
  names, so a backbone checkpoint is any checkpoint whose param paths
  prefix-match the model's, e.g. an SSD300 checkpoint warm-starting the
  ResNet-34 layers it shares).

  Returns (new_state, num_restored_leaves).
  """
  snapshot = load_checkpoint(path)
  restored = [0]

  def merge(collection, snap_tree):
    if snap_tree is None:
      return collection
    flat = jax.tree_util.tree_flatten_with_path(collection)[0]
    replacements = {}
    for key_path, leaf in flat:
      found = _lookup_path(snap_tree, key_path)
      if found is None:
        continue
      arr = np.asarray(found)
      if arr.shape == tuple(leaf.shape[1:]):  # leaf is replica-stacked
        replacements[key_path] = jnp.broadcast_to(
            jnp.asarray(arr, leaf.dtype)[None], leaf.shape)
        restored[0] += 1

    def rebuild(key_path, leaf):
      return replacements.get(key_path, leaf)

    return jax.tree_util.tree_map_with_path(rebuild, collection)

  params = merge(state.params, snapshot.get("params"))
  new_state = state.replace(
      params=params,
      batch_stats=merge(state.batch_stats, snapshot.get("batch_stats")),
      buffers=_reseed_staged(state.buffers, params))
  return new_state, restored[0]


def _reshard(template, host_tree):
  """Restore a FULL stacked shard tree (savable_state sharded layout)
  onto the live topology.

  Same shard count: every saved ``(n, k)`` array lands whole -- row i
  is device i's shard again -- instead of the v0 broadcast.

  Different shard count (the cross-mesh elastic rescale): the stacked
  layout is, by construction (ops/sharded.py stacked_shards), the
  row-major zero-padded flatten of the full state tensor -- so the
  saved ``(n, k)`` stack flattens back to the padded vector, is
  re-padded/truncated to the live ``n' * k'`` total (only zero pad is
  ever cut: ``n' * ceil(size / n') >= size`` for every ``n'``), and
  reshaped ``(n', k')``. Bit-exact: no shard value is recomputed, only
  re-addressed. Per-shard SCALAR leaves (optax schedule counts, shape
  ``(n,)`` under the vmap'd init) are replica-identical by construction
  -- every shard applies once per step -- so row 0 broadcasts to
  ``(n',)``."""
  host_state = serialization.from_state_dict(
      jax.tree.map(np.asarray, template), host_tree)

  def place(t, h):
    h = np.asarray(h)
    if tuple(h.shape) == tuple(t.shape):
      return jnp.asarray(h, t.dtype)
    if h.ndim == 1 and t.ndim == 1:
      # Stacked per-shard scalars: rows identical, re-stack to n'.
      return jnp.broadcast_to(jnp.asarray(h[0], t.dtype), t.shape)
    if h.ndim == 2 and t.ndim == 2:
      flat = h.reshape(-1)
      need = int(t.shape[0]) * int(t.shape[1])
      if need <= flat.size:
        flat = flat[:need]
      else:
        flat = np.pad(flat, (0, need - flat.size))
      return jnp.asarray(flat.reshape(tuple(t.shape)), t.dtype)
    if h.ndim == 3 and t.ndim == 3 and h.shape[1] == t.shape[1]:
      # FSDP scanned-stack leaves, (n, L, k) -> (n', L, k'): the same
      # flat re-address applied PER LAYER (ops/sharded.py
      # fsdp_stacked_shards stacks each layer's padded flat vector
      # independently, so layer l's rows re-slice exactly like a 2-D
      # (n, k) stack). Only zero pad is ever cut, as in the 2-D case.
      n_layers = h.shape[1]
      per_layer = np.moveaxis(h, 1, 0).reshape(n_layers, -1)
      need = int(t.shape[0]) * int(t.shape[2])
      if need <= per_layer.shape[1]:
        per_layer = per_layer[:, :need]
      else:
        per_layer = np.pad(per_layer,
                           ((0, 0), (0, need - per_layer.shape[1])))
      out = np.moveaxis(
          per_layer.reshape(n_layers, int(t.shape[0]), int(t.shape[2])),
          0, 1)
      return jnp.asarray(out, t.dtype)
    raise ValueError(
        f"sharded state leaf shape {h.shape} cannot be resliced "
        f"onto live {tuple(t.shape)}: only stacked (n, k) shard rows, "
        "(n, L, k) per-layer stacks of the same depth, and (n,) "
        "per-shard scalars have a defined cross-topology layout "
        "(ops/sharded.py)")

  return jax.tree.map(place, template, host_state)


def _deshard_params(template, host_tree):
  """FSDP shard stacks (savable_state ``params_layout: sharded``) ->
  full host param tree, re-assembled against the LIVE replicated
  template's shapes (leaves ``(n_replicas, *full)``).

  Pure host numpy: the flat row-major addressing of
  ops/sharded.fsdp_stacked_shards is inverted exactly -- concat the
  shard rows, drop the zero pad, restore the full shape (per layer for
  the 3-D ``(n, L, k)`` scanned stacks). Serves the eval-side restore
  (restore_opt_state=False) so FSDP checkpoints stay readable by
  replicated-param consumers."""
  host_state = serialization.from_state_dict(
      jax.tree.map(np.asarray, template), host_tree)

  def f(t, h):
    h = np.asarray(h)
    full_shape = tuple(t.shape)[1:]
    size = (int(np.prod(full_shape, dtype=np.int64))
            if full_shape else 1)
    if h.ndim == 2:
      return h.reshape(-1)[:size].reshape(full_shape)
    if h.ndim == 3 and full_shape and h.shape[1] == full_shape[0]:
      n_layers = h.shape[1]
      per_layer = size // n_layers
      return np.moveaxis(h, 1, 0).reshape(
          n_layers, -1)[:, :per_layer].reshape(full_shape)
    raise ValueError(
        f"FSDP param leaf shape {h.shape} cannot de-shard onto the "
        f"full shape {full_shape}: only (n, k) stacks and (n, L, k) "
        "per-layer stacks of matching depth have a defined layout "
        "(ops/sharded.py)")

  return serialization.to_state_dict(
      jax.tree.map(f, template, host_state))


def _restack(template, host_tree):
  """Saved trees round-trip through msgpack state-dict form (namedtuples
  become dicts), so restore via flax serialization against the live
  replica-0 template, then broadcast back to the stacked layout."""
  host_state = serialization.from_state_dict(
      jax.tree.map(lambda x: np.asarray(x[0]), template), host_tree)
  return jax.tree.map(
      lambda t, h: jnp.broadcast_to(jnp.asarray(h, t.dtype)[None], t.shape),
      template, host_state)
