"""Metrics fabric: unified metric registry, cross-run record store with
a regression sentinel, and a live ``/metrics`` endpoint.

TPU-native re-design of the reference's result-upload path: the
reference ships every run's numbers off-host -- tf_cnn_benchmarks'
BenchmarkLogger writes structured JSON metric/run files an uploader
ships to BigQuery (ref: benchmark_cnn.py:1594-1608 benchmark_log_dir
plumbing, logs the same ``average_examples_per_sec`` rows this module
registers), and the keras_benchmarks project uploads straight to
BigQuery (SURVEY §0 item 2) -- so results accumulate in a queryable
store. Here the same capability is host-local and dependency-free,
with three coupled pieces:

* **MetricRegistry** -- the typed schema (``SCHEMA``) is the single
  source of every metric key the framework emits: benchmark run stats,
  bench.py's one-line JSON, telemetry health keys, tracing latency
  percentiles and DeviceFeeder stats all render from keys registered
  here. The hazard lint (``analysis/lint.py`` rule
  ``metric-key-literal``) bans metric-key construction outside this
  schema; ``schema_audit`` cross-checks the registry against what the
  emitters actually produce.
* **Run-record store** -- every run appends ONE schema-versioned JSON
  line (config fingerprint from
  ``analysis/baseline.config_fingerprint_key``, git rev, jax version,
  platform, full metric snapshot) to an append-only JSONL store, with
  a query/merge API and a noise-aware (MAD-based) **regression
  sentinel** (``check_regression``). The first real-chip record per
  fingerprint auto-promotes to baseline, so the queued chip campaign
  (ROADMAP re-anchor note) self-baselines the moment the tunnel is
  healthy. ``python -m kf_benchmarks_tpu.metrics backfill`` ingests
  the committed ``BENCH_r0*.json`` history.
* **Live endpoint** -- an opt-in stdlib HTTP thread
  (``--metrics_port``; port + rank under kfrun) serving ``/metrics``
  in Prometheus text exposition format straight from the registry and
  ``/healthz`` from watchdog + flight-recorder state. Host-side only:
  the metrics-on step program is structurally identical to the
  metrics-off golden (``analysis/audit.rule_metrics_twin``, the
  twin-trace pattern).

Pure stdlib and host-only. Loadable standalone by file path (the
``run_tests.py --audit`` metrics-schema leg does exactly that); when
path-loaded, the percentile math is taken from ``tracing.py`` loaded
the same way, so the quantile convention stays single-sourced without
importing the (jax-importing) package.
"""

from __future__ import annotations

import collections
import http.server
import json
import math
import os
import re
import socketserver
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

if __package__:
  from kf_benchmarks_tpu import tracing as _tracing
else:  # loaded by file path (run_tests.py --audit): stay stdlib-only
  import importlib.util as _ilu

  def _load_tracing():
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tracing.py")
    spec = _ilu.spec_from_file_location("kf_metrics_tracing", path)
    mod = _ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod

  _tracing = _load_tracing()


# -- schema -------------------------------------------------------------------

# Dimensional labels (round 21): the registered label-name universe. A
# SCHEMA entry declares which of these its series may carry
# (``MetricSpec.labels``); the registry rejects any other label name at
# publish time -- the runtime half of the single-source contract, with
# the metric-key-literal lint's label leg as the static half.
LABEL_NAMES = ("tenant", "bucket", "shed_reason")


class MetricSpec(NamedTuple):
  name: str
  kind: str    # "counter" | "gauge" | "histogram" | "info"
  unit: str
  help: str
  source: str  # producing subsystem
  # Label names (each in LABEL_NAMES) this metric's series may carry;
  # () = a plain single-series metric.
  labels: Tuple[str, ...] = ()
  # Regression-sentinel direction: True = bigger is healthier
  # (throughput), False = smaller is (latency, shed), None = the
  # sentinel never gates this key directly. schema_audit REQUIRES a
  # non-None direction on every percentile/throughput/burn gauge.
  higher_is_better: Optional[bool] = None


SCHEMA: "collections.OrderedDict[str, MetricSpec]" = \
    collections.OrderedDict()

# The in-step health vector's key order (telemetry.health_finalize
# builds it; telemetry.py re-exports this tuple -- the ONE copy).
HEALTH_KEYS = ("grad_norm", "update_ratio", "nonfinite_leaves",
               "loss_scale", "skipped")
# Run-end health summary keys (FlightRecorder.summary + watchdog).
HEALTH_SUMMARY_KEYS = ("records", "max_grad_norm", "nonfinite_steps",
                       "loss_scale_final", "anomaly_dumps",
                       "watchdog_stalls")


def health_key(name: str) -> str:
  """The ``health/<key>`` namespace -- the ONE place that prefix is
  constructed (flight-recorder rows, summary scalars and the registry
  all share it; the metric-key-literal lint bans building it
  elsewhere)."""
  return "health/" + name


def _register(name: str, kind: str, unit: str, help_: str, source: str,
              labels: Tuple[str, ...] = (),
              higher_is_better: Optional[bool] = None) -> str:
  if name in SCHEMA:
    raise ValueError(f"duplicate metric key: {name}")
  for lab in labels:
    if lab not in LABEL_NAMES:
      # Unregistered label names fail AT REGISTRATION, exactly like
      # unregistered keys fail at publish -- both are schema typos.
      raise ValueError(f"unregistered label name {lab!r} on {name!r}: "
                       f"LABEL_NAMES is {LABEL_NAMES}")
  SCHEMA[name] = MetricSpec(name, kind, unit, help_, source,
                            tuple(labels), higher_is_better)
  return name


def _gauge(name, unit, help_, source, labels=(), higher_is_better=None):
  return _register(name, "gauge", unit, help_, source, labels,
                   higher_is_better)


def _counter(name, unit, help_, source, labels=()):
  return _register(name, "counter", unit, help_, source, labels)


def _hist(name, unit, help_, source, labels=(), higher_is_better=None):
  return _register(name, "histogram", unit, help_, source, labels,
                   higher_is_better)


def _info(name, help_, source):
  return _register(name, "info", "", help_, source)


# Benchmark run stats (benchmark.py _benchmark_train / forward / eval).
_gauge("images_per_sec", "images/s",
       "Timed-loop throughput (the headline metric)", "benchmark",
       higher_is_better=True)
_gauge("average_wall_time", "s", "Mean wall time per step", "benchmark",
       higher_is_better=False)
_gauge("last_average_loss", "1", "Loss of the last completed step",
       "benchmark")
_counter("num_steps", "steps", "Timed steps completed", "benchmark")
_counter("num_chunks", "chunks", "Timed K-step dispatches completed",
         "benchmark")
_gauge("num_workers", "processes", "Cooperating worker processes",
       "benchmark")
_gauge("steps_per_dispatch", "steps", "K of the chunked dispatch",
       "benchmark")
_gauge("compile_s", "s",
       "Wall of the first dispatch (blocks on trace+compile)",
       "benchmark", higher_is_better=False)
_gauge("dispatch_overhead_s", "s",
       "Mean host time per timed dispatch call (jit call + RTT)",
       "benchmark", higher_is_better=False)
_gauge("grad_noise_scale", "1", "EMA-smoothed B_simple estimate",
       "benchmark")
_gauge("opt_state_bytes_per_device", "bytes",
       "Per-device optimizer-state HBM", "benchmark")
_gauge("param_bytes_per_device", "bytes", "Per-device parameter HBM",
       "benchmark")
_gauge("feed_stall_fraction", "1",
       "Fraction of the consume window blocked on the host feed",
       "feeder", higher_is_better=False)
_gauge("packing_efficiency", "1",
       "Real-token fraction of the packed (B, T) grid", "feeder",
       higher_is_better=True)
_gauge("eval_images_per_sec", "images/s", "Eval-loop throughput",
       "benchmark", higher_is_better=True)
_gauge("top_1_accuracy", "1", "Eval top-1 accuracy", "benchmark",
       higher_is_better=True)
_gauge("top_5_accuracy", "1", "Eval top-5 accuracy", "benchmark",
       higher_is_better=True)

# Live training-loop gauges (the /metrics endpoint's per-step surface).
_counter("step", "steps", "Last completed global step", "benchmark")
_gauge("loss", "1", "Loss at the last completed step", "benchmark")
_gauge("learning_rate", "1", "Learning rate at the last completed step",
       "benchmark")
_gauge("step_images_per_sec", "images/s",
       "Throughput over the last display window", "benchmark",
       higher_is_better=True)

# Telemetry (telemetry.py): in-step health vector + run-end summary,
# all under the health/ namespace (health_key).
_gauge("health/grad_norm", "1", "Global gradient norm (in-step)",
       "telemetry")
_gauge("health/update_ratio", "1",
       "Update/param norm ratio (in-step)", "telemetry")
_gauge("health/nonfinite_leaves", "leaves",
       "Non-finite gradient leaves (in-step)", "telemetry")
_gauge("health/loss_scale", "1", "Loss scale (in-step)", "telemetry")
_gauge("health/skipped", "1", "Step skipped by the loss-scale machine",
       "telemetry")
_counter("health/records", "records", "Flight-recorder rows retained",
         "telemetry")
_gauge("health/max_grad_norm", "1", "Max global grad norm seen",
       "telemetry")
_counter("health/nonfinite_steps", "steps",
         "Steps with a non-finite training signal", "telemetry")
_gauge("health/loss_scale_final", "1", "Final loss scale", "telemetry")
_counter("health/anomaly_dumps", "dumps",
         "Flight-recorder anomaly episodes dumped", "telemetry")
_counter("health/watchdog_stalls", "stalls",
         "Stall-watchdog diagnostic episodes", "telemetry")

# Tracing (tracing.py): streaming latency percentiles over
# tracing.SAMPLE_KEYS x tracing.QUANTILES (schema_audit cross-checks
# this block against those tuples so the two cannot drift) + the
# compile-ledger aggregates.
_gauge("chunk_wall_p50", "s", "Chunk wall p50", "tracing",
       higher_is_better=False)
_gauge("chunk_wall_p90", "s", "Chunk wall p90", "tracing",
       higher_is_better=False)
_gauge("chunk_wall_p99", "s", "Chunk wall p99", "tracing",
       higher_is_better=False)
_gauge("feed_wait_p50", "s", "Feed wait p50", "tracing",
       higher_is_better=False)
_gauge("feed_wait_p90", "s", "Feed wait p90", "tracing",
       higher_is_better=False)
_gauge("feed_wait_p99", "s", "Feed wait p99", "tracing",
       higher_is_better=False)
_gauge("checkpoint_save_p50", "s", "Checkpoint save p50", "tracing",
       higher_is_better=False)
_gauge("checkpoint_save_p90", "s", "Checkpoint save p90", "tracing",
       higher_is_better=False)
_gauge("checkpoint_save_p99", "s", "Checkpoint save p99", "tracing",
       higher_is_better=False)
# Cumulative-histogram twins of the tracing SAMPLE_KEYS (round 21):
# the percentile gauges above remain the run-stats surface; these give
# the /metrics exposition a true le-bucket histogram a scraper can
# aggregate across scrapes and ranks (feed_wait already had its
# feed_wait_s twin below -- this completes the set, which schema_audit
# now pins against tracing.SAMPLE_KEYS). The serving pair carries the
# tenant label.
_hist("chunk_wall_s", "s", "Chunk wall distribution", "tracing",
      higher_is_better=False)
_hist("checkpoint_save_s", "s", "Checkpoint save distribution",
      "tracing", higher_is_better=False)
_counter("compile_ledger/shapes", "programs",
         "Distinct program shapes compiled", "tracing")
_counter("compile_ledger/total_compile_s", "s",
         "Total compile wall seconds", "tracing")

# Serving engine (serving/engine.py): request-path counters/gauges plus
# the TTFT / per-token latency percentiles (the serving/* sample keys
# in tracing.SAMPLE_KEYS render onto the _p50/_p90/_p99 keys here, so
# the cross-check in schema_audit covers them like every other sampled
# latency).
_counter("serving/requests", "requests", "Requests submitted", "serving",
         labels=("tenant",))
_counter("serving/completed", "requests", "Requests served to completion",
         "serving", labels=("tenant",))
_counter("serving/shed", "requests",
         "Requests shed by admission control (rejected + expired)",
         "serving", labels=("tenant", "shed_reason"))
_counter("serving/decode_steps", "steps", "Decode steps dispatched",
         "serving", labels=("bucket",))
_gauge("serving/shed_fraction", "1", "Shed fraction of all arrivals",
       "serving", labels=("tenant",), higher_is_better=False)
_gauge("serving/queue_depth", "requests",
       "Admission queue depth (mean at tick time)", "serving",
       higher_is_better=False)
_gauge("serving/batch_fill_fraction", "1",
       "Mean active-slot fraction of the decode bucket", "serving",
       higher_is_better=True)
_gauge("serving/active", "requests", "In-flight requests decoding",
       "serving")
_gauge("serving/decode_bucket", "requests",
       "Current bucket-ladder decode batch width", "serving")
_gauge("serving/tokens_per_sec", "tokens/s",
       "Generated-token throughput over the serve window", "serving",
       labels=("tenant",), higher_is_better=True)
_gauge("serving/ttft_p50", "s", "Time-to-first-token p50", "serving",
       labels=("tenant",), higher_is_better=False)
_gauge("serving/ttft_p90", "s", "Time-to-first-token p90", "serving",
       labels=("tenant",), higher_is_better=False)
_gauge("serving/ttft_p99", "s", "Time-to-first-token p99", "serving",
       labels=("tenant",), higher_is_better=False)
_gauge("serving/token_latency_p50", "s", "Per-token decode latency p50",
       "serving", labels=("tenant",), higher_is_better=False)
_gauge("serving/token_latency_p90", "s", "Per-token decode latency p90",
       "serving", labels=("tenant",), higher_is_better=False)
_gauge("serving/token_latency_p99", "s", "Per-token decode latency p99",
       "serving", labels=("tenant",), higher_is_better=False)
_hist("serving/ttft_s", "s", "Time-to-first-token distribution",
      "serving", labels=("tenant",), higher_is_better=False)
_hist("serving/token_latency_s", "s",
      "Per-token decode latency distribution", "serving",
      labels=("tenant",), higher_is_better=False)
_hist("serving/accept_len", "tokens",
      "Accepted speculative prefix length distribution", "serving",
      higher_is_better=True)
# Per-tenant SLO burn rates (round 21, SLOMonitor): error rate over
# error budget on a fast and a slow sliding window (the multi-window
# burn-rate alerting idiom); 1.0 = consuming exactly the budget,
# sustained >= the threshold on BOTH windows fires one alert episode.
_gauge("serving/slo_ttft_burn_fast", "x_budget",
       "TTFT-deadline objective burn rate (fast window)", "serving",
       labels=("tenant",), higher_is_better=False)
_gauge("serving/slo_ttft_burn_slow", "x_budget",
       "TTFT-deadline objective burn rate (slow window)", "serving",
       labels=("tenant",), higher_is_better=False)
_gauge("serving/slo_shed_burn_fast", "x_budget",
       "Shed-fraction objective burn rate (fast window)", "serving",
       labels=("tenant",), higher_is_better=False)
_gauge("serving/slo_shed_burn_slow", "x_budget",
       "Shed-fraction objective burn rate (slow window)", "serving",
       labels=("tenant",), higher_is_better=False)
_gauge("serving/slo_alerts", "episodes",
       "SLO alert episodes currently firing", "serving",
       labels=("tenant",), higher_is_better=False)
# Decode-cost variants (ISSUE 16): paged-KV occupancy and speculative
# accept accounting. Variant-off engines report these as None, which
# the publish path drops.
_gauge("serving/kv_pages_in_use", "pages",
       "Peak KV pool pages allocated to live requests", "serving")
_gauge("serving/kv_page_fraction", "1",
       "Peak allocated fraction of the KV page pool", "serving")
_counter("serving/spec_rounds", "rounds",
         "Speculative draft-propose/target-verify rounds", "serving")
_counter("serving/draft_tokens", "tokens",
         "Draft-model proposal tokens offered to the verifier",
         "serving")
_counter("serving/accepted_tokens", "tokens",
         "Draft proposals accepted by the target verifier", "serving")
_gauge("serving/accept_len_p50", "tokens",
       "Accepted speculative prefix length p50", "serving",
       higher_is_better=True)
_gauge("serving/accept_len_p90", "tokens",
       "Accepted speculative prefix length p90", "serving",
       higher_is_better=True)
_gauge("serving/accept_len_p99", "tokens",
       "Accepted speculative prefix length p99", "serving",
       higher_is_better=True)

# DeviceFeeder (data/device_feed.py): run-end stats + live lanes.
_counter("fetches", "batches", "Batches delivered to the consumer",
         "feeder")
_gauge("consumer_wait_s", "s", "Total consumer blocked-wait time",
       "feeder", higher_is_better=False)
_gauge("window_s", "s", "Wall window spanning the fetches", "feeder")
_gauge("queue_depth", "batches", "Prefetch queue depth at last fetch",
       "feeder")
_gauge("queue_depth_mean", "batches", "Mean queue depth at fetch time",
       "feeder")
_gauge("queue_depth_max", "batches", "Max queue depth at fetch time",
       "feeder")
_gauge("prefetch_batches", "batches", "Configured prefetch depth",
       "feeder")
_hist("feed_wait_s", "s", "Per-fetch consumer blocked-wait", "feeder")

# bench.py's one-line JSON (fields not covered above).
_gauge("vs_baseline", "1",
       "Headline value over the reference's committed baseline",
       "bench", higher_is_better=True)
_gauge("retries", "probes", "TPU probe attempts beyond the first",
       "bench")
_info("mesh_shape", "Mesh topology the run executed on", "benchmark")
_info("run_id", "Run id shared with trace + flight recorder",
      "benchmark")
_info("git_rev", "Git revision the run was built from", "bench")
_info("platform", "Execution platform (tpu | cpu)", "bench")
_info("metric", "Headline metric name", "bench")
_info("unit", "Headline metric unit", "bench")
# Round 20: which partitioner shaped the sharded step's collectives --
# "manual" (hand-written shard_map programs) or "gspmd" (plain jit +
# NamedShardings, XLA SPMD chooses the exchange). Provenance on the
# JSON line; the flag itself is program-shaping and keys the record's
# config fingerprint.
_info("partitioner", "Collective partitioner (manual | gspmd)", "bench")
# Tuned-config provenance (--autotuned_config, analysis/autotune.py):
# flatten_stats expands the nested stats/bench-JSON payload onto these,
# so the run-store snapshot records WHICH table row shaped a run (the
# tuned knobs themselves are program-shaping params and already key
# the record's config fingerprint).
_info("tuned_config_path", "Tuned-config table the run applied",
      "autotune")
_info("tuned_config_entry", "Matched tuned-table entry fingerprint",
      "autotune")

# Run-stats / bench-JSON keys that are bookkeeping, not metrics: the
# schema audit accepts them from the emitters without registration.
NON_METRIC_KEYS = frozenset({
    "state", "stopped_early", "restart_for_resize", "reshape_events",
    "aot_load_path", "value", "entries", "health",
    "latency_percentiles", "compile_ledger", "tuned_config",
    # Round 19: the serving bench's decode-variant identity block
    # ({quantize, paged_kv, speculative_k}) -- config provenance on the
    # JSON line, not a measurement; the same fields fold into the
    # record's fingerprint via the spec config.
    "decode_variant",
    # The int8 accuracy-gate evidence ({agreement, max_logit_delta,
    # passed}) behind a quantized serving line -- a measured decision
    # record, not a throughput metric.
    "quantize_gate",
    # Round 21: the serving bench's per-tenant block ({tenant:
    # {registered key: value, "serving/shed": {reason: n}, ...}}) --
    # flatten_stats expands it onto tenant-labeled registered keys for
    # the run-store snapshot; the nested form keeps the JSON line
    # readable per tenant.
    "serving_tenants",
})

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def prometheus_name(key: str) -> str:
  return "kf_" + _PROM_NAME_RE.sub("_", key)


# -- labeled keys -------------------------------------------------------------
#
# A labeled series flattens onto ONE string key -- Prometheus's own
# canonical form, ``name{a="x",b="y"}`` with label names sorted -- so
# run-store snapshots, registry storage and the exposition all share
# one encoding (and one parser).

_LABELED_KEY_RE = re.compile(r"^([^{}]+)\{(.*)\}$")
_LABEL_ITEM_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape_label(v: str) -> str:
  return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def labeled_key(name: str, labels: Optional[Dict[str, Any]]) -> str:
  """Canonical flat key of a (metric, labels) series; the bare name
  when ``labels`` is empty."""
  if not labels:
    return name
  body = ",".join(f'{k}="{_escape_label(str(v))}"'
                  for k, v in sorted(labels.items()))
  return f"{name}{{{body}}}"


def parse_labeled_key(key: str) -> Tuple[str, Dict[str, str]]:
  """(base name, labels dict) of a flat key; plain keys give an empty
  dict. Raises ValueError on a malformed label block."""
  if "{" not in key:
    return key, {}
  m = _LABELED_KEY_RE.match(key)
  if not m:
    raise ValueError(f"malformed labeled metric key {key!r}")
  body = m.group(2)
  items = _LABEL_ITEM_RE.findall(body)
  rebuilt = ",".join(f'{k}="{v}"' for k, v in items)
  if rebuilt != body:
    raise ValueError(f"malformed labeled metric key {key!r}")
  return m.group(1), {k: _unescape_label(v) for k, v in items}


# -- registry -----------------------------------------------------------------

# Cumulative-histogram bucket boundaries (le is inclusive; +Inf is
# implicit as the overflow bin). Seconds-scale latencies by default; a
# token-count histogram (unit "tokens") gets integer-ish bounds.
HIST_BUCKETS_SECONDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                        0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
HIST_BUCKETS_TOKENS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


def hist_buckets(spec: MetricSpec) -> Tuple[float, ...]:
  return (HIST_BUCKETS_TOKENS if spec.unit == "tokens"
          else HIST_BUCKETS_SECONDS)


class MetricRegistry:
  """Typed, thread-safe value store over the SCHEMA.

  Producers set/inc/observe REGISTERED keys only -- an unknown key
  raises, which is the runtime half of the single-source contract (the
  lint rule is the static half). Labeled series pass
  ``labels={name: value}`` with names declared on the key's SCHEMA
  entry -- an undeclared label name raises exactly like an
  unregistered key. Purely host-side: no jax, no device work, cheap
  enough to update per completed step.
  """

  def __init__(self):
    self._lock = threading.Lock()
    # Flat (possibly labeled) key -> value; histogram rows are
    # [count, sum, per-bin counts] over hist_buckets + the +Inf bin --
    # bounded memory by construction, no sample decimation needed.
    self._values: Dict[str, float] = {}
    self._info: Dict[str, str] = {}
    self._hists: Dict[str, list] = {}

  @staticmethod
  def _spec(name: str) -> MetricSpec:
    spec = SCHEMA.get(name)
    if spec is None:
      raise ValueError(
          f"unregistered metric key {name!r}: register it in "
          "kf_benchmarks_tpu/metrics.py SCHEMA (the single source of "
          "metric keys; see the metric-key-literal lint rule)")
    return spec

  @staticmethod
  def _key(spec: MetricSpec, labels: Optional[Dict[str, Any]]) -> str:
    if not labels:
      return spec.name
    for lab in labels:
      if lab not in spec.labels:
        raise ValueError(
            f"unregistered label name {lab!r} on metric "
            f"{spec.name!r}: its SCHEMA entry declares {spec.labels!r} "
            "(labels are single-sourced in metrics.py LABEL_NAMES / "
            "the registration)")
    return labeled_key(spec.name, labels)

  def set(self, name: str, value,
          labels: Optional[Dict[str, Any]] = None) -> None:
    spec = self._spec(name)
    key = self._key(spec, labels)
    with self._lock:
      if spec.kind == "info":
        if labels:
          raise ValueError(f"{name} is info-kind; it renders as a "
                           "kf_run_info label and takes no labels")
        self._info[key] = str(value)
      elif spec.kind == "histogram":
        raise ValueError(f"{name} is a histogram; use observe()")
      else:
        self._values[key] = float(value)

  def inc(self, name: str, delta: float = 1.0,
          labels: Optional[Dict[str, Any]] = None) -> None:
    spec = self._spec(name)
    if spec.kind != "counter":
      raise ValueError(f"{name} is a {spec.kind}; inc() is counter-only")
    key = self._key(spec, labels)
    with self._lock:
      self._values[key] = self._values.get(key, 0.0) + float(delta)

  def observe(self, name: str, value: float,
              labels: Optional[Dict[str, Any]] = None) -> None:
    spec = self._spec(name)
    if spec.kind != "histogram":
      raise ValueError(f"{name} is a {spec.kind}; observe() is "
                       "histogram-only")
    key = self._key(spec, labels)
    bounds = hist_buckets(spec)
    v = float(value)
    with self._lock:
      row = self._hists.setdefault(key, [0, 0.0,
                                         [0] * (len(bounds) + 1)])
      row[0] += 1
      row[1] += v
      i = 0
      while i < len(bounds) and v > bounds[i]:
        i += 1
      row[2][i] += 1

  def snapshot(self) -> Dict[str, Any]:
    """Flat {key: value} of every set scalar/info value (labeled series
    under their canonical ``name{...}`` keys); histograms surface as
    <key>/count and <key>/sum for the run record."""
    with self._lock:
      out: Dict[str, Any] = dict(self._values)
      out.update(self._info)
      hists = {k: (row[0], row[1]) for k, row in self._hists.items()}
    for k, (count, total) in hists.items():
      base, labels = parse_labeled_key(k)
      out[labeled_key(base + "/count", labels)] = count
      out[labeled_key(base + "/sum", labels)] = total
    return out

  def render(self) -> str:
    """Prometheus text exposition format (version 0.0.4), straight
    from the registry: labeled series group under one HELP/TYPE block
    per metric, histogram-kind metrics render as true cumulative
    histograms (``_bucket{le=...}`` + ``_sum`` + ``_count``), and
    info-kind values collapse into one ``kf_run_info`` labeled gauge
    (the Prometheus info-metric idiom)."""
    with self._lock:
      values = dict(self._values)
      info = dict(self._info)
      hists = {k: (row[0], row[1], list(row[2]))
               for k, row in self._hists.items()}
    lines: List[str] = []

    def _suffix(labels: Dict[str, str], extra: str = "") -> str:
      body = ",".join(f'{_PROM_NAME_RE.sub("_", k)}='
                      f'"{_escape_label(v)}"'
                      for k, v in sorted(labels.items()))
      if extra:
        body = f"{body},{extra}" if body else extra
      return "{%s}" % body if body else ""

    by_base: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for key, value in values.items():
      base, labels = parse_labeled_key(key)
      by_base.setdefault(base, []).append((labels, value))
    for base in sorted(by_base):
      spec = SCHEMA[base]
      prom = prometheus_name(base)
      lines.append(f"# HELP {prom} {spec.help} [{spec.unit}]")
      lines.append(f"# TYPE {prom} {spec.kind}")
      for labels, value in sorted(by_base[base],
                                  key=lambda p: sorted(p[0].items())):
        lines.append(f"{prom}{_suffix(labels)} {_fmt_value(value)}")
    hist_by_base: Dict[str, List[Tuple[Dict[str, str], tuple]]] = {}
    for key, row in hists.items():
      base, labels = parse_labeled_key(key)
      hist_by_base.setdefault(base, []).append((labels, row))
    for base in sorted(hist_by_base):
      spec = SCHEMA[base]
      prom = prometheus_name(base)
      bounds = hist_buckets(spec)
      lines.append(f"# HELP {prom} {spec.help} [{spec.unit}]")
      lines.append(f"# TYPE {prom} histogram")
      for labels, (count, total, bins) in sorted(
          hist_by_base[base], key=lambda p: sorted(p[0].items())):
        running = 0
        for bound, n in zip(bounds, bins):
          running += n
          le = _suffix(labels, f'le="{_fmt_value(bound)}"')
          lines.append(f"{prom}_bucket{le} {running}")
        le = _suffix(labels, 'le="+Inf"')
        lines.append(f"{prom}_bucket{le} {count}")
        lines.append(f"{prom}_sum{_suffix(labels)} {_fmt_value(total)}")
        lines.append(f"{prom}_count{_suffix(labels)} {count}")
    if info:
      labels = ",".join(
          f'{_PROM_NAME_RE.sub("_", k)}="{_escape_label(v)}"'
          for k, v in sorted(info.items()))
      lines.append("# HELP kf_run_info Run identity labels")
      lines.append("# TYPE kf_run_info gauge")
      lines.append("kf_run_info{%s} 1" % labels)
    return "\n".join(lines) + "\n"


def _fmt_value(v: float) -> str:
  if math.isnan(v):
    return "NaN"
  if math.isinf(v):
    return "+Inf" if v > 0 else "-Inf"
  return format(float(v), ".10g")


def _escape_label(v: str) -> str:
  return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
      "\n", "\\n")


_PROM_LINE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^{}]*\})? "
    r"(NaN|[+-]Inf|[-+0-9.eE]+)$")
_PROM_LABEL_ITEM_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_prom_labels(body: Optional[str]):
  """{...} label body -> dict, or None on malformed body."""
  if not body:
    return {}
  inner = body[1:-1]
  items = _PROM_LABEL_ITEM_RE.findall(inner)
  if ",".join(f'{k}="{v}"' for k, v in items) != inner:
    return None
  return dict(items)


def validate_prometheus_text(text: str) -> List[str]:
  """Structural check of a Prometheus text-format payload; returns
  problem strings (empty = valid). Beyond line grammar this checks the
  cumulative-histogram contract promtool enforces: every
  ``<name>_bucket`` series needs an ``le`` label, each (family,
  labels) series needs a ``+Inf`` bucket with monotone non-decreasing
  cumulative counts, and ``<name>_count`` must equal the ``+Inf``
  bucket. The schema contract the endpoint tests and the
  metrics-schema audit pin."""
  problems = []
  # (family, frozen non-le labels) -> [(le, count)], and _count values.
  # Only families DECLARED "# TYPE <fam> histogram" get the histogram
  # suffix treatment -- a plain gauge whose name happens to end in
  # _bucket (serving/decode_bucket) must not trip the grammar.
  hist_families = set()
  buckets: Dict[Tuple[str, frozenset], List[Tuple[str, float]]] = {}
  counts: Dict[Tuple[str, frozenset], float] = {}
  for i, line in enumerate(text.splitlines()):
    if not line.strip():
      continue
    if line.startswith("# TYPE "):
      parts = line.split()
      if len(parts) != 4 or parts[3] not in (
          "counter", "gauge", "summary", "histogram", "untyped"):
        problems.append(f"line {i}: bad TYPE line {line!r}")
      elif parts[3] == "histogram":
        hist_families.add(parts[2])
      continue
    if line.startswith("#"):
      continue
    m = _PROM_LINE_RE.match(line)
    if not m:
      problems.append(f"line {i}: not a metric sample: {line!r}")
      continue
    name, body, value = m.group(1), m.group(2), m.group(3)
    labels = _parse_prom_labels(body)
    if labels is None:
      problems.append(f"line {i}: malformed label body: {line!r}")
      continue
    if name.endswith("_bucket") and name[:-len("_bucket")] in \
        hist_families:
      le = labels.pop("le", None)
      if le is None:
        problems.append(f"line {i}: _bucket sample without le label: "
                        f"{line!r}")
        continue
      series = (name[:-len("_bucket")], frozenset(labels.items()))
      buckets.setdefault(series, []).append((le, float(value)))
    elif name.endswith("_count") and name[:-len("_count")] in \
        hist_families:
      counts[(name[:-len("_count")], frozenset(labels.items()))] = \
          float(value)
  for series, rows in buckets.items():
    fam = series[0]
    les = [le for le, _ in rows]
    if "+Inf" not in les:
      problems.append(f"histogram {fam}: series missing +Inf bucket")
    vals = [n for _, n in rows]
    if any(b < a for a, b in zip(vals, vals[1:])):
      problems.append(f"histogram {fam}: bucket counts not cumulative "
                      f"monotone: {vals}")
    if "+Inf" in les and series in counts:
      inf = dict(rows)["+Inf"]
      if counts[series] != inf:
        problems.append(f"histogram {fam}: _count {counts[series]} != "
                        f"+Inf bucket {inf}")
  return problems


# -- stats flattening (run stats / bench JSON -> registered keys) -------------

def flatten_stats(stats: Dict[str, Any]) -> Dict[str, Any]:
  """One flat {registered key: value} view of a benchmark stats dict or
  a bench.py JSON record: nested health / latency_percentiles /
  compile_ledger containers expand onto their registered keys,
  bookkeeping keys (NON_METRIC_KEYS) and unset values drop out.
  The serving engine's per-tenant block (``serving_tenants``) expands
  onto labeled keys (``name{tenant="..."}``; shed counts additionally
  carry ``shed_reason``)."""
  out: Dict[str, Any] = {}
  for key, value in (stats or {}).items():
    if value is None:
      continue
    if key == "serving_tenants" and isinstance(value, dict):
      for tenant, block in value.items():
        if not isinstance(block, dict):
          continue
        for tk, tv in block.items():
          if tv is None:
            continue
          if tk == "serving/shed" and isinstance(tv, dict):
            for reason, n in tv.items():
              out[labeled_key("serving/shed",
                              {"tenant": tenant,
                               "shed_reason": reason})] = float(n)
            continue
          if tk in SCHEMA and isinstance(tv, (int, float)) \
              and not isinstance(tv, bool):
            out[labeled_key(tk, {"tenant": tenant})] = float(tv)
      continue
    if key == "health" and isinstance(value, dict):
      for hk, hv in value.items():
        name = health_key(hk)
        if name in SCHEMA and isinstance(hv, (int, float)):
          out[name] = float(hv)
      continue
    if key == "latency_percentiles" and isinstance(value, dict):
      for lk, lv in value.items():
        if lk in SCHEMA and lv is not None:
          out[lk] = float(lv)
      continue
    if key == "compile_ledger" and isinstance(value, dict):
      for ck in ("shapes", "total_compile_s"):
        if value.get(ck) is not None:
          out["compile_ledger/" + ck] = float(value[ck])
      continue
    if key == "tuned_config" and isinstance(value, dict):
      if value.get("path"):
        out["tuned_config_path"] = str(value["path"])
      if value.get("entry"):
        out["tuned_config_entry"] = str(value["entry"])
      continue
    spec = SCHEMA.get(key)
    if spec is None:
      continue
    if spec.kind == "info":
      out[key] = str(value)
    elif isinstance(value, bool):
      out[key] = float(value)
    elif isinstance(value, (int, float)):
      out[key] = float(value)
  return out


def publish_stats(registry, stats: Dict[str, Any]) -> None:
  """Render a stats dict into a registry (the run-end publication the
  /metrics endpoint serves after the loop completes)."""
  for key, value in flatten_stats(stats).items():
    base, labels = parse_labeled_key(key)
    if SCHEMA[base].kind == "histogram":
      continue
    registry.set(base, value, labels=labels or None)


# -- active-registry (the tracing.py pattern) ---------------------------------

class _NullRegistry:
  """No-op sink with the MetricRegistry surface, so deep producers
  (DeviceFeeder's consumer path) publish unconditionally."""

  def set(self, *a, **k) -> None:
    pass

  def inc(self, *a, **k) -> None:
    pass

  def observe(self, *a, **k) -> None:
    pass

  def snapshot(self) -> Dict[str, Any]:
    return {}

  def render(self) -> str:
    return "\n"


NULL_REGISTRY = _NullRegistry()
_active: Any = None


def activate(registry: MetricRegistry) -> MetricRegistry:
  global _active
  _active = registry
  return registry


def deactivate() -> None:
  global _active
  _active = None


def active():
  """The process's active MetricRegistry, or the no-op sink."""
  return _active if _active is not None else NULL_REGISTRY


# -- live endpoint ------------------------------------------------------------

def resolve_port(base_port: int, rank: int = 0) -> int:
  """Per-rank port under kfrun: rank r serves base + r (every worker of
  a single-host job gets its own scrape target)."""
  return int(base_port) + int(rank)


class _Handler(http.server.BaseHTTPRequestHandler):
  server_version = "kf-metrics/1"

  def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
    path = self.path.split("?", 1)[0]
    if path == "/metrics":
      body = self.server.registry.render().encode("utf-8")
      ctype = "text/plain; version=0.0.4; charset=utf-8"
    elif path == "/healthz":
      try:
        payload = self.server.healthz_fn()
      except Exception as e:  # a health probe must answer, not raise
        payload = {"status": "error", "error": repr(e)}
      body = (json.dumps(payload) + "\n").encode("utf-8")
      ctype = "application/json"
    else:
      self.send_error(404, "unknown path (serving /metrics, /healthz)")
      return
    self.send_response(200)
    self.send_header("Content-Type", ctype)
    self.send_header("Content-Length", str(len(body)))
    self.end_headers()
    self.wfile.write(body)

  def log_message(self, *args) -> None:
    pass  # scrapes must never interleave into the run's stdout


class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
  daemon_threads = True
  # Scrape targets restart with the run; a lingering TIME_WAIT socket
  # must not fail the next run's bind.
  allow_reuse_address = True


class MetricsServer:
  """Opt-in scrape endpoint on a daemon thread.

  Binds eagerly (a bad port fails fast at session start, not at first
  scrape); ``port=0`` binds an ephemeral port -- ``self.port`` is
  always the real bound port. Host-side only by construction: the
  handler reads the registry under its lock and never touches jax.
  """

  def __init__(self, registry, port: int, host: str = "127.0.0.1",
               healthz_fn: Optional[Callable[[], Dict[str, Any]]] = None):
    self._httpd = _Server((host, int(port)), _Handler)
    self._httpd.registry = registry
    self._httpd.healthz_fn = healthz_fn or (lambda: {"status": "ok"})
    self.host = host
    self.port = int(self._httpd.server_address[1])
    self._thread = threading.Thread(
        target=self._httpd.serve_forever, name="kf-metrics-endpoint",
        daemon=True)
    self._thread.start()

  def close(self) -> None:
    self._httpd.shutdown()
    self._httpd.server_close()
    self._thread.join(timeout=5.0)


# -- SLO burn-rate monitor ----------------------------------------------------

SLO_OBJECTIVES = ("ttft_deadline", "shed_fraction")


class SLOMonitor:
  """Multi-window error-budget burn-rate monitor (the Google SRE
  alerting shape): per (objective, tenant) stream of good/bad events,
  burn = bad_fraction / error_budget over a fast and a slow sliding
  window, and an alert fires only when BOTH windows burn at or above
  the threshold -- fast alone is noise, slow alone is stale.

  Alerts are DATA, never exceptions (the serving shed discipline):
  edge-triggered episode records (one ``firing``, one ``resolved``)
  append to ``self.alerts`` and, when a flight recorder is attached,
  ride its row stream via ``note_event`` so the post-run report and
  the live ``/healthz`` agree. Host-only, stdlib-only, fake-clock
  testable via ``time_fn``.
  """

  def __init__(self, objectives: Optional[Dict[str, float]] = None,
               fast_window_s: float = 15.0, slow_window_s: float = 60.0,
               burn_threshold: float = 2.0,
               time_fn: Callable[[], float] = time.monotonic,
               recorder=None):
    objectives = dict(objectives if objectives is not None
                      else {o: 0.99 for o in SLO_OBJECTIVES})
    for obj, target in objectives.items():
      if obj not in SLO_OBJECTIVES:
        raise ValueError(f"unknown SLO objective {obj!r}: "
                         f"SLO_OBJECTIVES is {SLO_OBJECTIVES}")
      if not 0.0 < float(target) < 1.0:
        raise ValueError(f"SLO target for {obj!r} must be in (0, 1), "
                         f"got {target!r}")
    self.objectives = {k: float(v) for k, v in objectives.items()}
    self.fast_window_s = float(fast_window_s)
    self.slow_window_s = float(slow_window_s)
    self.burn_threshold = float(burn_threshold)
    self._time = time_fn
    self._recorder = recorder
    self._lock = threading.Lock()
    # (objective, tenant) -> deque[(t, good)] pruned past slow window.
    self._events: Dict[Tuple[str, str], "collections.deque"] = {}
    self._firing: Dict[Tuple[str, str], bool] = {}
    self.alerts: List[Dict[str, Any]] = []

  def observe(self, objective: str, tenant: str, good: bool,
              t: Optional[float] = None) -> None:
    if objective not in self.objectives:
      raise ValueError(f"unknown SLO objective {objective!r}: this "
                       f"monitor tracks {sorted(self.objectives)}")
    t = self._time() if t is None else float(t)
    key = (objective, str(tenant))
    with self._lock:
      q = self._events.setdefault(key, collections.deque())
      q.append((t, bool(good)))
      self._prune(q, t)
      self._evaluate(key, t)

  def _prune(self, q, t: float) -> None:
    horizon = t - self.slow_window_s
    while q and q[0][0] < horizon:
      q.popleft()

  def burn(self, objective: str, tenant: str,
           t: Optional[float] = None) -> Dict[str, Optional[float]]:
    """{"fast": burn, "slow": burn}; None where the window is empty."""
    t = self._time() if t is None else float(t)
    budget = max(1.0 - self.objectives[objective], 1e-9)
    with self._lock:
      q = self._events.get((objective, str(tenant))) or ()
      rows = list(q)
    out: Dict[str, Optional[float]] = {}
    for name, win in (("fast", self.fast_window_s),
                      ("slow", self.slow_window_s)):
      inside = [good for (et, good) in rows if et >= t - win]
      if not inside:
        out[name] = None
      else:
        bad = sum(1 for good in inside if not good)
        out[name] = (bad / len(inside)) / budget
    return out

  def _evaluate(self, key: Tuple[str, str], t: float) -> None:
    # Caller holds the lock via observe(); burn() re-takes it, so
    # compute inline over the already-pruned deque.
    objective, tenant = key
    budget = max(1.0 - self.objectives[objective], 1e-9)
    rows = list(self._events.get(key) or ())
    burns = {}
    for name, win in (("fast", self.fast_window_s),
                      ("slow", self.slow_window_s)):
      inside = [good for (et, good) in rows if et >= t - win]
      burns[name] = None if not inside else \
          (sum(1 for g in inside if not g) / len(inside)) / budget
    hot = (burns["fast"] is not None and burns["slow"] is not None
           and burns["fast"] >= self.burn_threshold
           and burns["slow"] >= self.burn_threshold)
    was = self._firing.get(key, False)
    if hot == was:
      return
    self._firing[key] = hot
    rec = {
        "slo_alert": objective,
        "tenant": tenant,
        "state": "firing" if hot else "resolved",
        "burn_fast": burns["fast"],
        "burn_slow": burns["slow"],
        "threshold": self.burn_threshold,
        "budget": budget,
        "t": t,
    }
    self.alerts.append(rec)
    if self._recorder is not None:
      self._recorder.note_event(dict(rec))

  def firing(self, t: Optional[float] = None) -> List[Tuple[str, str]]:
    """Currently-firing (objective, tenant) streams. Re-evaluates every
    stream at ``t`` first, so a quiet recovery (no new events) still
    clears -- the probe IS the evaluation tick."""
    t = self._time() if t is None else float(t)
    with self._lock:
      for key, q in self._events.items():
        self._prune(q, t)
        self._evaluate(key, t)
      return sorted(k for k, hot in self._firing.items() if hot)

  def state(self, t: Optional[float] = None) -> Dict[str, Any]:
    """The /healthz payload: per-objective per-tenant burn rates plus
    the episode count; status "burning" iff any stream fires."""
    t = self._time() if t is None else float(t)
    hot = self.firing(t)
    objectives: Dict[str, Any] = {}
    with self._lock:
      keys = sorted(self._events)
    for objective, tenant in keys:
      burns = self.burn(objective, tenant, t)
      objectives.setdefault(objective, {})[tenant] = {
          "burn_fast": burns["fast"],
          "burn_slow": burns["slow"],
          "firing": (objective, tenant) in hot,
      }
    return {
        "status": "burning" if hot else "ok",
        "threshold": self.burn_threshold,
        "objectives": objectives,
        "alerts": len(self.alerts),
    }


# -- run-record store ---------------------------------------------------------

RECORD_SCHEMA_VERSION = 1
STORE_FILENAME = "run_store.jsonl"


def run_record(*, metric: str, value: float, unit: str,
               fingerprint: str, run_id: str, platform: str,
               fallback: bool = False, git_rev: Optional[str] = None,
               jax_version: Optional[str] = None,
               snapshot: Optional[Dict[str, Any]] = None,
               t_wall: Optional[float] = None) -> Dict[str, Any]:
  """One schema-versioned run record. ``fingerprint`` is the program
  identity (analysis/baseline.config_fingerprint_key) the sentinel
  compares within; ``fallback`` marks a ``_CPU_FALLBACK`` probe so it
  can never enter a chip baseline; ``snapshot`` is the flat registered
  metric view (flatten_stats / MetricRegistry.snapshot)."""
  return {
      "schema_version": RECORD_SCHEMA_VERSION,
      "t_wall": round(float(time.time() if t_wall is None else t_wall),
                      3),
      "run_id": str(run_id),
      "fingerprint": str(fingerprint),
      "metric": str(metric),
      "value": float(value),
      "unit": str(unit),
      "platform": str(platform),
      "fallback": bool(fallback),
      "baseline": False,
      "git_rev": git_rev,
      "jax_version": jax_version,
      "snapshot": dict(snapshot or {}),
  }


def validate_record(rec) -> List[str]:
  """Problem strings (empty = valid) for one store record -- the
  schema-version contract the metrics-schema audit re-checks over the
  whole store."""
  problems = []
  if not isinstance(rec, dict):
    return ["record is not an object"]
  ver = rec.get("schema_version")
  if not isinstance(ver, int) or not 1 <= ver <= RECORD_SCHEMA_VERSION:
    problems.append(f"schema_version {ver!r} outside "
                    f"[1, {RECORD_SCHEMA_VERSION}]")
  for field in ("run_id", "fingerprint", "metric", "unit", "platform"):
    v = rec.get(field)
    if not isinstance(v, str) or not v:
      problems.append(f"{field} missing or not a non-empty string")
  v = rec.get("value")
  if not isinstance(v, (int, float)) or isinstance(v, bool) or \
      not math.isfinite(v):
    problems.append(f"value {v!r} is not a finite number")
  if not isinstance(rec.get("t_wall"), (int, float)):
    problems.append("t_wall missing or not a number")
  for field in ("fallback", "baseline"):
    if not isinstance(rec.get(field), bool):
      problems.append(f"{field} missing or not a bool")
  snap = rec.get("snapshot")
  if not isinstance(snap, dict):
    problems.append("snapshot missing or not an object")
  else:
    for k, sv in snap.items():
      try:
        base, labels = parse_labeled_key(k)
      except ValueError:
        problems.append(f"snapshot key {k!r} is a malformed labeled key")
        continue
      base = base.split("/count")[0].split("/sum")[0]
      spec = SCHEMA.get(base)
      if spec is None:
        problems.append(f"snapshot key {k!r} not in the metric schema")
        continue
      bad = [lab for lab in labels if lab not in spec.labels]
      if bad:
        problems.append(f"snapshot key {k!r} carries undeclared label "
                        f"names {bad} (declared: {list(spec.labels)})")
      elif not isinstance(sv, (int, float, str)):
        problems.append(f"snapshot value for {k!r} is {type(sv).__name__}")
  return problems


class RunStore:
  """Append-only JSONL store of run records.

  One line per run; torn/foreign lines are skipped on read (the store
  rides ordinary filesystems and a crashed writer must not poison the
  history). ``append`` validates and auto-promotes the first real-chip
  record of a fingerprint to baseline.
  """

  def __init__(self, store_dir: str, filename: str = STORE_FILENAME):
    self.dir = str(store_dir)
    self.path = os.path.join(self.dir, filename)

  def records(self) -> List[Dict[str, Any]]:
    out = []
    try:
      with open(self.path, encoding="utf-8") as f:
        for line in f:
          line = line.strip()
          if not line:
            continue
          try:
            rec = json.loads(line)
          except ValueError:
            continue
          if isinstance(rec, dict) and "metric" in rec:
            out.append(rec)
    except OSError:
      pass
    return out

  def query(self, fingerprint: Optional[str] = None,
            metric: Optional[str] = None,
            fallback: Optional[bool] = None) -> List[Dict[str, Any]]:
    rows = self.records()
    if fingerprint is not None:
      rows = [r for r in rows if r.get("fingerprint") == fingerprint]
    if metric is not None:
      rows = [r for r in rows if r.get("metric") == metric]
    if fallback is not None:
      rows = [r for r in rows if bool(r.get("fallback")) == fallback]
    rows.sort(key=lambda r: r.get("t_wall", 0.0))
    return rows

  def has_run(self, run_id: str, metric: str) -> bool:
    return any(r.get("run_id") == run_id and r.get("metric") == metric
               for r in self.records())

  def append(self, rec: Dict[str, Any]) -> Dict[str, Any]:
    problems = validate_record(rec)
    if problems:
      raise ValueError("invalid run record: " + "; ".join(problems))
    if rec["platform"] == "tpu" and not rec["fallback"] and \
        not rec["baseline"]:
      # Baseline self-promotion: the FIRST real-chip record per
      # fingerprint becomes the baseline, so the reserved chip campaign
      # baselines itself the moment the tunnel is healthy. _CPU_FALLBACK
      # rows (fallback=True) and CPU runs are never eligible.
      prior = [r for r in self.records()
               if r.get("fingerprint") == rec["fingerprint"]
               and r.get("baseline")]
      if not prior:
        rec = dict(rec, baseline=True)
    os.makedirs(self.dir, exist_ok=True)
    with open(self.path, "a", encoding="utf-8") as f:
      f.write(json.dumps(rec, sort_keys=True) + "\n")
    return rec

  @staticmethod
  def merge(paths: List[str]) -> List[Dict[str, Any]]:
    """Union of several store files, deduped on (run_id, metric,
    t_wall) -- the cross-host merge for stores synced from more than
    one machine."""
    seen = set()
    out = []
    for path in paths:
      for rec in RunStore(os.path.dirname(path) or ".",
                          os.path.basename(path)).records():
        key = (rec.get("run_id"), rec.get("metric"), rec.get("t_wall"))
        if key in seen:
          continue
        seen.add(key)
        out.append(rec)
    out.sort(key=lambda r: r.get("t_wall", 0.0))
    return out


# -- regression sentinel ------------------------------------------------------

# Consistent MAD->sigma factor for normal noise.
MAD_SIGMA = 1.4826
# Defaults tuned to the acceptance bar: a seeded 20% throughput drop
# flags against any realistic history, +-5% run-to-run noise stays
# quiet (uniform +-5% noise has MAD ~2.5%, so the MAD leg of the bar
# sits at ~15%; a noise-free history floors the bar at rel_floor).
SENTINEL_WINDOW = 8
SENTINEL_MAD_FACTOR = 4.0
SENTINEL_REL_FLOOR = 0.08
SENTINEL_MIN_HISTORY = 3


def check_regression(history: List[Dict[str, Any]],
                     fresh: Dict[str, Any],
                     window: int = SENTINEL_WINDOW,
                     mad_factor: float = SENTINEL_MAD_FACTOR,
                     rel_floor: float = SENTINEL_REL_FLOOR,
                     min_history: int = SENTINEL_MIN_HISTORY,
                     higher_is_better: bool = True) -> Dict[str, Any]:
  """Compare ``fresh`` against the trailing median of comparable
  history with a noise-aware bar.

  Comparable = same fingerprint, same metric name, same fallback
  status (a ``_CPU_FALLBACK`` probe never judges -- or joins -- a chip
  baseline), excluding the fresh run itself. The bar is
  ``max(mad_factor * 1.4826 * MAD, rel_floor * |median|)``: the MAD leg
  adapts to the config's measured run-to-run noise, the relative floor
  keeps a noise-free history from flagging epsilon jitter.
  """
  rows = [r for r in history
          if r.get("fingerprint") == fresh.get("fingerprint")
          and r.get("metric") == fresh.get("metric")
          and bool(r.get("fallback")) == bool(fresh.get("fallback"))
          and r.get("run_id") != fresh.get("run_id")]
  rows.sort(key=lambda r: r.get("t_wall", 0.0))
  tail = rows[-max(1, int(window)):]
  value = float(fresh.get("value", float("nan")))
  base = {
      "metric": fresh.get("metric"),
      "fingerprint": fresh.get("fingerprint"),
      "value": value,
      "n": len(tail),
      "window": int(window),
  }
  if len(tail) < min_history:
    return dict(base, status="no_history", median=None, bar=None)
  vals = [float(r["value"]) for r in tail]
  med = _tracing.percentile(vals, 50)
  mad = _tracing.percentile([abs(v - med) for v in vals], 50)
  bar = max(mad_factor * MAD_SIGMA * mad, rel_floor * abs(med))
  delta = (med - value) if higher_is_better else (value - med)
  status = "regression" if delta > bar else "ok"
  return dict(base, status=status, median=med, bar=bar)


def verdict_line(verdict: Dict[str, Any]) -> str:
  """One whole self-identifying verdict line (the scrape-guard
  discipline: never interleaves inside the bench JSON line)."""
  metric = verdict.get("metric")
  fp = (verdict.get("fingerprint") or "")[:16]
  if verdict["status"] == "no_history":
    return (f"regression check: NO HISTORY for {metric} "
            f"(fingerprint {fp}, {verdict['n']} comparable record(s)); "
            "recorded as history for future runs")
  word = "REGRESSION" if verdict["status"] == "regression" else "OK"
  return ("regression check: %s %s value=%.3f median=%.3f bar=%.3f "
          "(n=%d, fingerprint %s)" % (
              word, metric, verdict["value"], verdict["median"],
              verdict["bar"], verdict["n"], fp))


# Direction fallback for keys whose SCHEMA entry predates (or lacks)
# higher_is_better -- substring heuristics, first match wins.
_DIRECTION_HINTS = (
    ("per_sec", True),
    ("accuracy", True),
    ("ttft", False),
    ("latency", False),
    ("shed", False),
    ("wall", False),
)


def metric_direction(name: str) -> bool:
  """higher_is_better for a (possibly labeled) metric key: the SCHEMA
  field when set, else a name heuristic, else True (the pre-label
  sentinel default, so old throughput records keep their polarity)."""
  base, _ = parse_labeled_key(name)
  base = base.split("/count")[0].split("/sum")[0]
  spec = SCHEMA.get(base)
  if spec is not None and spec.higher_is_better is not None:
    return spec.higher_is_better
  for needle, better in _DIRECTION_HINTS:
    if needle in base:
      return better
  return True


def snapshot_check(history: List[Dict[str, Any]],
                   fresh: Dict[str, Any],
                   key: str) -> Optional[Dict[str, Any]]:
  """Direction-aware sentinel over a SNAPSHOT key instead of the
  headline metric: synthesizes per-key rows from the stored snapshots
  and runs check_regression with the key's SCHEMA direction. Returns
  None when the fresh record has no such snapshot key (the variant is
  off)."""
  if key not in (fresh.get("snapshot") or {}):
    return None

  def _row(rec):
    snap = rec.get("snapshot") or {}
    if key not in snap or not isinstance(snap[key], (int, float)):
      return None
    return {
        "fingerprint": rec.get("fingerprint"),
        "metric": key,
        "fallback": rec.get("fallback"),
        "run_id": rec.get("run_id"),
        "t_wall": rec.get("t_wall", 0.0),
        "value": float(snap[key]),
    }

  hist_rows = [r for r in map(_row, history) if r is not None]
  fresh_row = _row(fresh)
  return check_regression(hist_rows, fresh_row,
                          higher_is_better=metric_direction(key))


# -- bench identity (shared by bench.py and the backfill CLI) -----------------

def bench_params_kwargs(on_tpu: bool) -> Dict[str, Any]:
  """The canonical headline-bench config (bench.py's make_params call)
  -- ONE copy, so a backfilled record and a fresh bench run compute the
  same config fingerprint."""
  return dict(
      model="resnet50",
      batch_size=256 if on_tpu else 8,
      num_batches=None if on_tpu else 5,
      num_warmup_batches=None if on_tpu else 1,
      device="tpu" if on_tpu else "cpu",
      num_devices=1,
      variable_update="replicated",
      use_fp16=on_tpu,
      optimizer="momentum",
      display_every=10,
      health_stats=True,
  )


def bench_fingerprint(on_tpu: bool, params=None) -> str:
  """Config fingerprint of the headline bench (program name "bench").

  ``params`` is the RESOLVED Params when the caller has them (bench.py
  after setup -- so a tuned-table application keys the record under
  the knobs it actually ran with, never the canonical defaults; the
  run store must not mix tuned and default runs under one
  fingerprint). Imports the params registry lazily (jax-adjacent);
  when that import is unavailable (path-loaded stdlib context) the key
  degrades to a stable legacy tag so backfill still produces
  comparable history."""
  try:
    from kf_benchmarks_tpu import params as params_lib
    from kf_benchmarks_tpu.analysis import baseline as baseline_lib
  except ImportError:  # the designed degrade: no package/jax available
    return "bench-legacy-" + ("tpu" if on_tpu else "cpu")
  if params is None:
    params = params_lib.make_params(**bench_params_kwargs(on_tpu))
  return baseline_lib.config_fingerprint_key(params._asdict(), "bench")


def git_revision(repo_dir: Optional[str] = None) -> Optional[str]:
  """Short git revision of ``repo_dir`` (default: this repo), or None
  when git/metadata is unavailable -- a missing rev must never fail a
  bench run."""
  import subprocess
  cwd = repo_dir or os.path.dirname(os.path.dirname(
      os.path.abspath(__file__)))
  try:
    out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                         capture_output=True, text=True, cwd=cwd,
                         timeout=10)
  except (OSError, subprocess.SubprocessError):
    return None
  rev = (out.stdout or "").strip()
  return rev if out.returncode == 0 and rev else None


# -- backfill -----------------------------------------------------------------

def bench_rows(path: str) -> List[Dict[str, Any]]:
  """The bench record(s) inside one ``BENCH_*.json`` artifact.

  Two committed shapes: the driver wrapper (one pretty-printed object
  whose ``parsed`` field holds bench.py's one-line record -- the
  ``BENCH_r0*.json`` history) and raw bench JSONL (one record per
  line). Anything else yields nothing."""
  try:
    text = open(path, encoding="utf-8").read()
  except OSError:
    return []
  try:
    obj = json.loads(text)
  except ValueError:
    obj = None
  if isinstance(obj, dict):
    row = obj.get("parsed") if isinstance(obj.get("parsed"),
                                          dict) else obj
    return [row] if "metric" in row else []
  out = []
  for line in text.splitlines():
    line = line.strip()
    if not line:
      continue
    try:
      row = json.loads(line)
    except ValueError:
      continue
    if isinstance(row, dict) and "metric" in row:
      out.append(row)
  return out


def _backfill_ordinal(name: str, line: int) -> int:
  """Synthetic t_wall for a backfilled row: historical files carry no
  timestamp, so the ordinal is derived from the FILE NAME (first 16
  bytes, big-endian) -- monotone in lexicographic name order and
  stable under later insertions (a BENCH_r02 committed after r03 was
  already ingested still sorts between r01 and r03, unlike a
  position-index scheme). Offset far negative so every backfilled row
  sorts BEFORE any real wall-clock record; exact integer arithmetic
  end to end (floats would eat the low-order name bytes)."""
  prefix = name.encode("utf-8", "replace")[:16].ljust(16, b"\0")
  return int.from_bytes(prefix, "big") * 4096 + int(line) - 2 ** 141


def backfill(repo_dir: str, store_dir: Optional[str] = None,
             pattern: str = r"BENCH_.*\.json$",
             log: Callable[[str], None] = print) -> Tuple[int, int]:
  """Ingest the committed ``BENCH_*.json`` history into the run store
  so the sentinel has history on day one. ``_CPU_FALLBACK`` rows are
  tagged ``fallback`` (never baseline-eligible). Idempotent: rows
  already in the store (by backfill run id + metric) are skipped.
  Returns (ingested, skipped)."""
  store = RunStore(store_dir or repo_dir)
  rx = re.compile(pattern)
  ingested = skipped = 0
  names = sorted(n for n in os.listdir(repo_dir) if rx.match(n))
  for name in names:
    path = os.path.join(repo_dir, name)
    rows = bench_rows(path)
    if not rows:
      log(f"backfill: no bench record in {name}; skipped")
      continue
    stem = os.path.splitext(name)[0]
    for i, row in enumerate(rows):
      if row.get("value") is None:
        skipped += 1
        continue
      metric = str(row["metric"])
      fallback = "_CPU_FALLBACK" in metric
      run_id = f"backfill-{stem}" + (f"-{i + 1}" if len(rows) > 1
                                     else "")
      if store.has_run(run_id, metric):
        skipped += 1
        continue
      rec = run_record(
          metric=metric, value=float(row["value"]),
          unit=str(row.get("unit") or "1"),
          fingerprint=bench_fingerprint(on_tpu=not fallback),
          run_id=run_id,
          platform="cpu" if fallback else "tpu",
          fallback=fallback,
          git_rev=row.get("git_rev"),
          jax_version=row.get("jax_version"),
          snapshot=flatten_stats(row))
      # Past run_record's float rounding: the ordinal needs exact
      # integer ordering (see _backfill_ordinal).
      rec["t_wall"] = _backfill_ordinal(name, i)
      store.append(rec)
      ingested += 1
      log(f"backfill: {name} -> {metric} = {row['value']}"
          + (" [fallback]" if fallback else ""))
  log(f"backfill: {ingested} record(s) ingested, {skipped} skipped "
      f"-> {store.path}")
  return ingested, skipped


# -- schema audit (the run_tests.py --audit leg) ------------------------------

def _ast_emitted_keys(path: str) -> List[Tuple[str, int]]:
  """Literal keys of the metric-emitting dicts in a source file: any
  dict literal that carries an ``images_per_sec`` key (the benchmark
  stats dicts) or both ``metric`` and ``value`` (the bench JSON
  record), plus ``record["..."]``-style subscript assignments onto
  such a dict's name."""
  import ast
  try:
    tree = ast.parse(open(path, encoding="utf-8").read())
  except (OSError, SyntaxError):
    return []
  out = []
  for node in ast.walk(tree):
    if not isinstance(node, ast.Dict):
      continue
    keys = [k.value for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)]
    if "images_per_sec" in keys or {"metric", "value"} <= set(keys):
      out.extend((k, node.lineno) for k in keys)
  for node in ast.walk(tree):
    if (isinstance(node, ast.Assign) and len(node.targets) == 1
        and isinstance(node.targets[0], ast.Subscript)
        and isinstance(node.targets[0].value, ast.Name)
        and node.targets[0].value.id == "record"
        and isinstance(node.targets[0].slice, ast.Constant)
        and isinstance(node.targets[0].slice.value, str)):
      out.append((node.targets[0].slice.value, node.lineno))
  return out


def schema_audit(repo_dir: str) -> List[str]:
  """The metrics-schema audit: registry keys vs what the emitters
  actually produce, plus store-record validity. Pure host-side, no
  device work (the ``run_tests.py --audit`` budget). Returns problem
  strings (empty = clean)."""
  problems: List[str] = []
  # 1. Schema self-consistency: prometheus names must stay distinct
  # after sanitization (two keys mapping to one exposition name would
  # silently merge on the endpoint).
  prom_names: Dict[str, str] = {}
  for name in SCHEMA:
    prom = prometheus_name(name)
    if prom in prom_names:
      problems.append(f"schema: {name!r} and {prom_names[prom]!r} both "
                      f"render as {prom}")
    prom_names[prom] = name
  # 2. Health namespace coverage: every key telemetry can emit is
  # registered.
  for k in HEALTH_KEYS + HEALTH_SUMMARY_KEYS:
    if health_key(k) not in SCHEMA:
      problems.append(f"schema: telemetry key {health_key(k)!r} is not "
                      "registered")
  # 3. Tracing coverage: every SAMPLE_KEYS x QUANTILES percentile field
  # and the ledger aggregates are registered (the registration block is
  # literal for the lint; this is its staleness check) -- and every
  # sample stream also has a cumulative-histogram twin (key or key_s)
  # so the exposition carries the full distribution, not just
  # precomputed quantile gauges.
  for key in _tracing.SAMPLE_KEYS:
    for q in _tracing.QUANTILES:
      name = f"{key}_p{q}"
      if name not in SCHEMA:
        problems.append(f"schema: tracing percentile field {name!r} is "
                        "not registered")
    twins = [key, key + "_s"]
    if not any(SCHEMA.get(t) is not None and SCHEMA[t].kind == "histogram"
               for t in twins):
      problems.append(f"schema: tracing sample key {key!r} has no "
                      f"histogram-kind twin (looked for {twins})")
  # 3b. Label + direction validity: declared labels come from
  # LABEL_NAMES, higher_is_better is a tri-state bool, and direction
  # is REQUIRED on every key the sentinel or the fleet report can
  # judge (percentile gauges, throughputs, shed/burn rates).
  _needs_direction = re.compile(r"_p(50|90|99)$")
  for name, spec in SCHEMA.items():
    for lab in spec.labels:
      if lab not in LABEL_NAMES:
        problems.append(f"schema: {name!r} declares label {lab!r} "
                        f"outside LABEL_NAMES {LABEL_NAMES}")
    if spec.higher_is_better not in (True, False, None):
      problems.append(f"schema: {name!r} higher_is_better must be "
                      "True/False/None")
    if spec.kind == "gauge" and spec.higher_is_better is None and (
        _needs_direction.search(name) or "per_sec" in name
        or "shed_fraction" in name or "_burn_" in name):
      problems.append(f"schema: sentinel-judged gauge {name!r} has no "
                      "higher_is_better direction")
  # 4. Emitters: every literal key of the benchmark stats dicts and the
  # bench JSON record is registered or explicitly non-metric.
  for rel in ("kf_benchmarks_tpu/benchmark.py", "bench.py"):
    for key, lineno in _ast_emitted_keys(os.path.join(repo_dir, rel)):
      if key not in SCHEMA and key not in NON_METRIC_KEYS:
        problems.append(
            f"{rel}:{lineno}: emitted metric key {key!r} is neither "
            "registered in metrics.SCHEMA nor in NON_METRIC_KEYS")
  # 5. Committed bench history: every BENCH_*.json record field
  # flattens onto registered keys (the backfill contract).
  for name in sorted(os.listdir(repo_dir)):
    if not re.match(r"BENCH_.*\.json$", name):
      continue
    rows = bench_rows(os.path.join(repo_dir, name))
    if not rows:
      problems.append(f"{name}: no bench record found")
      continue
    for row in rows:
      for key, value in row.items():
        if key in NON_METRIC_KEYS or value is None:
          continue
        if key in ("health",):
          continue
        if key == "latency_percentiles" and isinstance(value, dict):
          for lk in value:
            if lk not in SCHEMA:
              problems.append(f"{name}: latency key {lk!r} unregistered")
          continue
        if key not in SCHEMA:
          problems.append(f"{name}: bench JSON key {key!r} is not in "
                          "the metric schema")
  # 6. Run store (when present): every record validates against the
  # current schema version.
  store = RunStore(repo_dir)
  for i, rec in enumerate(store.records()):
    for p in validate_record(rec):
      problems.append(f"{store.path}: record {i}: {p}")
  # 7. Exposition self-check: a fully-populated registry -- every key,
  # and a labeled series for every key that declares labels -- renders
  # valid Prometheus text including the cumulative-histogram grammar.
  reg = MetricRegistry()
  for name, spec in SCHEMA.items():
    labeled = {spec.labels[0]: "t0"} if spec.labels else None
    if spec.kind == "info":
      reg.set(name, "x")
    elif spec.kind == "histogram":
      reg.observe(name, 0.5)
      if labeled:
        reg.observe(name, 0.5, labels=labeled)
    elif spec.kind == "counter":
      reg.inc(name)
      if labeled:
        reg.inc(name, labels=labeled)
    else:
      reg.set(name, 1.5)
      if labeled:
        reg.set(name, 1.5, labels=labeled)
  problems.extend("prometheus render: " + p
                  for p in validate_prometheus_text(reg.render()))
  return problems


# -- fleet report (the BigQuery-dashboard replacement) ------------------------

def fleet_rows(records: List[Dict[str, Any]],
               fingerprint: Optional[str] = None,
               metric: Optional[str] = None,
               platform: Optional[str] = None,
               fallback: str = "all") -> List[Dict[str, Any]]:
  """Group store records into per-(fingerprint, metric) trend rows with
  a direction-aware verdict on the LATEST record vs its own trailing
  history. ``fingerprint`` is a prefix filter (verdict lines only print
  16 chars); ``fallback`` is "all" | "only" | "none"."""
  rows = []
  for rec in records:
    if validate_record(rec):
      continue
    if fingerprint and not rec["fingerprint"].startswith(fingerprint):
      continue
    if metric and rec["metric"] != metric:
      continue
    if platform and rec["platform"] != platform:
      continue
    if fallback == "only" and not rec["fallback"]:
      continue
    if fallback == "none" and rec["fallback"]:
      continue
    rows.append(rec)
  groups: Dict[Tuple[str, str, bool], List[Dict[str, Any]]] = {}
  for rec in rows:
    groups.setdefault(
        (rec["fingerprint"], rec["metric"], rec["fallback"]),
        []).append(rec)
  out = []
  for (fp, met, fb), rs in sorted(groups.items()):
    rs.sort(key=lambda r: r.get("t_wall", 0.0))
    values = [float(r["value"]) for r in rs]
    direction = metric_direction(met)
    verdict = check_regression(rs[:-1], rs[-1],
                               higher_is_better=direction)
    out.append({
        "fingerprint": fp,
        "metric": met,
        "unit": rs[-1].get("unit"),
        "platform": rs[-1].get("platform"),
        "fallback": fb,
        "n": len(rs),
        "values": values,
        "first": values[0],
        "last": values[-1],
        "median": _tracing.percentile(values, 50),
        "direction": direction,
        "verdict": verdict["status"],
        "records": rs,
    })
  return out


def format_fleet_report(rows: List[Dict[str, Any]]) -> str:
  """Aligned per-fingerprint trend table; the text half of the report
  CLI. Empty input explains itself (the backfill pointer) instead of
  printing a bare header."""
  if not rows:
    return ("fleet report: no matching run records. Populate the "
            "store first: python -m kf_benchmarks_tpu.metrics "
            "backfill (committed BENCH_*.json history) or any "
            "bench.py run.\n")
  header = ("FINGERPRINT", "METRIC", "N", "FIRST", "LAST", "MEDIAN",
            "BETTER", "VERDICT", "FLAGS")
  table = [header]
  for r in rows:
    flags = []
    if r["fallback"]:
      flags.append("_CPU_FALLBACK")
    if r["platform"]:
      flags.append(str(r["platform"]))
    table.append((
        r["fingerprint"][:16],
        r["metric"],
        str(r["n"]),
        "%.3f" % r["first"],
        "%.3f" % r["last"],
        "%.3f" % r["median"],
        "higher" if r["direction"] else "lower",
        r["verdict"],
        ",".join(flags),
    ))
  widths = [max(len(row[i]) for row in table)
            for i in range(len(header))]
  lines = ["  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
           for row in table]
  lines.append("fleet report: %d trend row(s) over %d record(s)" % (
      len(rows), sum(r["n"] for r in rows)))
  return "\n".join(lines) + "\n"


def _svg_sparkline(series: List[float], w: int = 220,
                   h: int = 48) -> str:
  """Self-contained inline-SVG sparkline (no JS, no external assets --
  the report file must open from an airgapped artifact store)."""
  pad = 4.0
  if not series:
    return f'<svg width="{w}" height="{h}"></svg>'
  lo, hi = min(series), max(series)
  span = (hi - lo) or 1.0

  def _xy(i, v):
    x = pad + (w - 2 * pad) * (i / max(1, len(series) - 1))
    y = pad + (h - 2 * pad) * (1.0 - (v - lo) / span)
    return f"{x:.1f},{y:.1f}"

  if len(series) == 1:
    x, y = _xy(0, series[0]).split(",")
    body = f'<circle cx="{x}" cy="{y}" r="3" fill="#36c"/>'
  else:
    pts = " ".join(_xy(i, v) for i, v in enumerate(series))
    body = (f'<polyline points="{pts}" fill="none" stroke="#36c" '
            'stroke-width="1.5"/>')
  return (f'<svg width="{w}" height="{h}" '
          f'viewBox="0 0 {w} {h}">{body}</svg>')


_SERVING_CURVE_KEYS = ("serving/ttft_p50", "serving/ttft_p90",
                       "serving/ttft_p99")
_CURVE_COLORS = ("#2a9d5c", "#e0a426", "#d0453e")


def fleet_report_html(rows: List[Dict[str, Any]]) -> str:
  """One self-contained HTML timeline: a sparkline per trend row,
  serving TTFT percentile curves where the snapshots carry them, and
  ``_CPU_FALLBACK`` probes segregated into their own greyed section so
  a tunnel-outage probe is never visually conflated with a chip
  trend."""
  import html as _html

  def _row_html(r):
    cells = [
        f"<td><code>{_html.escape(r['fingerprint'][:16])}</code></td>",
        f"<td>{_html.escape(r['metric'])}</td>",
        f"<td>{r['n']}</td>",
        f"<td>{r['last']:.3f} {_html.escape(str(r['unit'] or ''))}</td>",
        f"<td>{'higher' if r['direction'] else 'lower'}</td>",
        f"<td class=\"v-{_html.escape(r['verdict'])}\">"
        f"{_html.escape(r['verdict'])}</td>",
        f"<td>{_svg_sparkline(r['values'])}</td>",
    ]
    curves = []
    for key, color in zip(_SERVING_CURVE_KEYS, _CURVE_COLORS):
      series = [float(rec["snapshot"][key]) for rec in r["records"]
                if isinstance((rec.get("snapshot") or {}).get(key),
                              (int, float))]
      if series:
        curves.append(
            _svg_sparkline(series).replace("#36c", color))
    cells.append("<td>" + "".join(curves) + "</td>")
    return "<tr>" + "".join(cells) + "</tr>"

  head = ("<tr><th>fingerprint</th><th>metric</th><th>n</th>"
          "<th>last</th><th>better</th><th>verdict</th>"
          "<th>trend</th><th>serving ttft p50/p90/p99</th></tr>")
  live = [r for r in rows if not r["fallback"]]
  fell = [r for r in rows if r["fallback"]]
  sections = []
  if live:
    sections.append("<h2>Trends</h2><table>" + head
                    + "".join(_row_html(r) for r in live) + "</table>")
  if fell:
    sections.append('<div class="fallback"><h2>_CPU_FALLBACK probes '
                    "(tunnel outage; never baseline)</h2><table>"
                    + head + "".join(_row_html(r) for r in fell)
                    + "</table></div>")
  if not sections:
    sections.append("<p>No matching run records. Populate the store: "
                    "<code>python -m kf_benchmarks_tpu.metrics "
                    "backfill</code></p>")
  return (
      "<!doctype html><html><head><meta charset=\"utf-8\">"
      "<title>kf_benchmarks_tpu fleet report</title><style>"
      "body{font-family:sans-serif;margin:24px}"
      "table{border-collapse:collapse}"
      "td,th{border:1px solid #ccc;padding:4px 8px;text-align:left}"
      ".v-regression{color:#b00;font-weight:bold}"
      ".v-ok{color:#080}.v-no_history{color:#888}"
      ".fallback{opacity:0.55;filter:grayscale(1);margin-top:24px}"
      "</style></head><body><h1>kf_benchmarks_tpu fleet report</h1>"
      + "".join(sections) + "</body></html>\n")


# -- CLI ----------------------------------------------------------------------

def main(argv=None) -> int:
  import argparse
  repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  parser = argparse.ArgumentParser(
      prog="python -m kf_benchmarks_tpu.metrics",
      description="run-record store tools: backfill BENCH_*.json "
                  "history, audit the metric schema, render the "
                  "cross-run fleet report")
  sub = parser.add_subparsers(dest="cmd", required=True)
  p_back = sub.add_parser("backfill",
                          help="ingest BENCH_*.json into the run store")
  p_back.add_argument("--repo", default=repo)
  p_back.add_argument("--run_store_dir", default=None,
                      help="store directory (default: the repo root, "
                           "alongside the BENCH_*.json files)")
  p_audit = sub.add_parser("audit", help="metrics-schema audit")
  p_audit.add_argument("--repo", default=repo)
  p_rep = sub.add_parser(
      "report", help="per-fingerprint trend table from the run store")
  p_rep.add_argument("--repo", default=repo)
  p_rep.add_argument("--run_store_dir", default=None,
                     help="store directory (default: the repo root)")
  p_rep.add_argument("--html", default=None, metavar="OUT",
                     help="also write a self-contained HTML timeline")
  p_rep.add_argument("--fingerprint", default=None,
                     help="fingerprint prefix filter")
  p_rep.add_argument("--metric", default=None)
  p_rep.add_argument("--platform", default=None)
  p_rep.add_argument("--fallback", default="all",
                     choices=("all", "only", "none"),
                     help="_CPU_FALLBACK probes: include, only, or drop")
  args = parser.parse_args(argv)
  if args.cmd == "backfill":
    backfill(args.repo, args.run_store_dir)
    return 0
  if args.cmd == "report":
    store = RunStore(args.run_store_dir or args.repo)
    rows = fleet_rows(store.records(),
                      fingerprint=args.fingerprint,
                      metric=args.metric,
                      platform=args.platform,
                      fallback=args.fallback)
    print(format_fleet_report(rows), end="")
    if args.html:
      with open(args.html, "w") as f:
        f.write(fleet_report_html(rows))
      print(f"fleet report: wrote {args.html}")
    return 0
  problems = schema_audit(args.repo)
  for p in problems:
    print(p)
  print(f"metrics-schema audit: {len(problems)} problem(s)")
  return 1 if problems else 0


if __name__ == "__main__":
  raise SystemExit(main())
