"""Deterministic fault injection for preemption-safe training.

TPU-native-only subsystem with no reference analog: the reference's
elastic story delegates failure handling to KungFu's external runtime
and its tests never kill a worker. Here every failure mode the elastic
path must survive -- a preempted (SIGKILL'd) worker, a graceful SIGTERM
preemption notice, a stalled heartbeat, a dropped coordination message,
a checkpoint torn mid-write -- is a *named, step-keyed, reproducible*
event, so kill/rejoin survival is a test, not an anecdote.

Schedule grammar (``--fault_schedule``), pure stdlib so validation.py
and the hazard lint can parse it without jax::

    spec    := entry (',' entry)*
    entry   := kind '@' step (':' key '=' value)*
    kind    := kill | sigterm | heartbeat_delay | drop_msg | corrupt_ckpt
    keys    := rank=<int>   -- fire on this process rank only
               secs=<float> -- heartbeat_delay sleep length (default 3)

Examples::

    --fault_schedule=kill@10:rank=1          SIGKILL rank 1 after step 10
    --fault_schedule=sigterm@6               graceful preemption at step 6
    --fault_schedule=corrupt_ckpt@4,drop_msg@8

Semantics (all enforced by the injector, pinned in tests/test_faults.py):

* Faults fire at the *dispatch boundary* after the named step completes
  (benchmark.py shortens chunked dispatches so a chunk never crosses a
  fault step, exactly like checkpoints/eval/elastic polls).
* Each entry fires ONCE per run -- including across checkpoint-restart
  generations: fired entries are recorded in
  ``<train_dir>/faults_fired.rank<r>.json`` *before* the fault fires,
  so a kill at step 10 does not re-kill the rejoined worker when it
  replays past step 10 (the marker write precedes the SIGKILL).
* ``kill``/``sigterm`` deliver the real signal to this process
  (``os.kill``): SIGKILL is the preemption the process never sees;
  SIGTERM exercises the chained telemetry handlers (flight-recorder
  post-mortem, telemetry.py) end to end.
* ``heartbeat_delay`` sleeps on the host between dispatches, starving
  the stall watchdog's heartbeat -- the watchdog must diagnose and
  NEVER kill (CLAUDE.md wedge hazard).
* ``drop_msg`` suppresses the NEXT coordination-service poll (sticky
  across boundaries when the fault step is not itself a poll step):
  the elastic dedup must re-see a pending RESIZE on the following poll
  instead of losing it.
* ``corrupt_ckpt`` truncates the newest checkpoint file mid-record (a
  torn write): the restore path (``checkpoint.load_latest_checkpoint``)
  must skip it with a logged warning and resume from the previous one.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, NamedTuple, Optional


FAULT_KINDS = ("kill", "sigterm", "heartbeat_delay", "drop_msg",
               "corrupt_ckpt")


class FaultScheduleError(ValueError):
  """Malformed --fault_schedule (validation.py wraps it in ParamError)."""


class Fault(NamedTuple):
  index: int            # position in the schedule (the one-shot key)
  kind: str
  step: int
  rank: Optional[int]   # None = every rank
  secs: float           # heartbeat_delay length

  def describe(self) -> str:
    where = f" (rank {self.rank})" if self.rank is not None else ""
    extra = f" {self.secs:g}s" if self.kind == "heartbeat_delay" else ""
    return f"{self.kind}{extra} at step {self.step}{where}"


def parse_schedule(spec: str) -> List[Fault]:
  """``--fault_schedule`` string -> [Fault, ...]; FaultScheduleError on
  any malformed entry (validation rejects the config up front)."""
  faults = []
  for i, raw in enumerate(t for t in (spec or "").split(",") if t.strip()):
    entry = raw.strip()
    kind, at, rest = entry.partition("@")
    if not at or kind not in FAULT_KINDS:
      raise FaultScheduleError(
          f"--fault_schedule entry {entry!r}: expected "
          f"<kind>@<step>[:key=value...] with kind in {FAULT_KINDS}")
    parts = rest.split(":")
    try:
      step = int(parts[0])
    except ValueError:
      raise FaultScheduleError(
          f"--fault_schedule entry {entry!r}: step {parts[0]!r} is not "
          "an integer")
    if step < 1:
      raise FaultScheduleError(
          f"--fault_schedule entry {entry!r}: steps are 1-based (the "
          "fault fires after the named step completes)")
    rank, secs = None, 3.0
    for kv in parts[1:]:
      key, eq, value = kv.partition("=")
      try:
        if key == "rank" and eq:
          rank = int(value)
        elif key == "secs" and eq:
          secs = float(value)
        else:
          raise ValueError
      except ValueError:
        raise FaultScheduleError(
            f"--fault_schedule entry {entry!r}: unknown or malformed "
            f"modifier {kv!r} (known: rank=<int>, secs=<float>)")
    faults.append(Fault(index=i, kind=kind, step=step, rank=rank,
                        secs=secs))
  return faults


def _fired_path(state_dir: str, rank: int) -> str:
  return os.path.join(state_dir, f"faults_fired.rank{rank}.json")


class FiredFaults(NamedTuple):
  """What one dispatch boundary's injection did (benchmark.py consumes
  the flag it cannot apply itself)."""
  fired: List[Fault]
  dropped_message: bool   # suppress the next coordination poll


class FaultInjector:
  """Owns one process's schedule: rank filtering, one-shot persistence,
  and the firing of every kind that does not need the training loop's
  cooperation (drop_msg is returned as a flag instead -- the injector
  cannot reach into the elastic poll)."""

  def __init__(self, faults: List[Fault], rank: int = 0,
               state_dir: Optional[str] = None, log_fn=None):
    self.rank = int(rank)
    self.state_dir = state_dir
    self._log = log_fn or (lambda s: None)
    self._faults = [f for f in faults
                    if f.rank is None or f.rank == self.rank]
    self._fired = self._load_fired()

  @classmethod
  def from_params(cls, params, rank: int = 0, log_fn=None
                  ) -> Optional["FaultInjector"]:
    spec = getattr(params, "fault_schedule", None)
    if not spec:
      return None
    return cls(parse_schedule(spec), rank=rank,
               state_dir=getattr(params, "train_dir", None), log_fn=log_fn)

  # -- one-shot persistence ---------------------------------------------------

  def _load_fired(self) -> set:
    if not self.state_dir:
      return set()
    try:
      with open(_fired_path(self.state_dir, self.rank)) as f:
        return set(json.load(f))
    except (OSError, ValueError):
      return set()

  def _mark_fired(self, fault: Fault) -> None:
    """Persist BEFORE the fault fires: a kill must not re-fire when the
    rejoined worker replays past its step."""
    self._fired.add(fault.index)
    if not self.state_dir:
      return
    try:
      os.makedirs(self.state_dir, exist_ok=True)
      path = _fired_path(self.state_dir, self.rank)
      with open(path + ".tmp", "w") as f:
        json.dump(sorted(self._fired), f)
      os.replace(path + ".tmp", path)
    except OSError:
      pass  # unwritable sink: in-memory one-shot still holds

  # -- scheduling -------------------------------------------------------------

  def peek_due(self, step: int) -> List[Fault]:
    """The faults that WILL fire at this boundary, without firing them
    (the telemetry record must land before a kill does)."""
    return [f for f in self._faults
            if f.step == step and f.index not in self._fired]

  def due(self, step: int) -> bool:
    return bool(self.peek_due(step))

  # -- firing -----------------------------------------------------------------

  def fire_due(self, step: int, train_dir: Optional[str] = None
               ) -> FiredFaults:
    """Fire every due fault at this boundary. ``kill``/``sigterm`` do
    not return (the signal is the point); the others report what the
    caller must still apply."""
    fired: List[Fault] = []
    dropped = False
    # Local import: this module stays importable standalone (pure
    # stdlib; the hazard lint loads files by path), and the package
    # import would pull jax. tracing itself is stdlib-only.
    try:
      from kf_benchmarks_tpu import tracing
      trace = tracing.active()
    except Exception:
      trace = None
    for fault in self._faults:
      if fault.step != step or fault.index in self._fired:
        continue
      self._mark_fired(fault)
      fired.append(fault)
      if trace is not None:
        # Instant marker on the faults track BEFORE firing. The
        # survivable kinds (heartbeat_delay / drop_msg / corrupt_ckpt)
        # land in this rank's exported timeline; a kill/sigterm rank
        # loses its in-memory spans (the trace exports at run end), so
        # the durable record of those is the flight-recorder row the
        # driver writes before this boundary fires (benchmark.py) --
        # the recorder's continuous window hits disk every step.
        trace.instant("faults", fault.describe(), step=step,
                      kind=fault.kind)
      self._log(f"fault injected: {fault.describe()}")
      if fault.kind == "kill":
        import signal
        os.kill(os.getpid(), signal.SIGKILL)  # never returns
      elif fault.kind == "sigterm":
        import signal
        # Through the real delivery path so the chained telemetry
        # handlers (flight-recorder post-mortem) run exactly as they
        # would on an operator preemption notice.
        os.kill(os.getpid(), signal.SIGTERM)
      elif fault.kind == "heartbeat_delay":
        time.sleep(fault.secs)
      elif fault.kind == "drop_msg":
        dropped = True
      elif fault.kind == "corrupt_ckpt":
        self._corrupt_newest_checkpoint(train_dir or self.state_dir)
    return FiredFaults(fired=fired, dropped_message=dropped)

  def _corrupt_newest_checkpoint(self, train_dir: Optional[str]) -> None:
    """Truncate the newest checkpoint mid-record -- the torn-write state
    a SIGTERM mid-save would have left WITHOUT the atomic tmp+replace
    protocol (checkpoint.py); resume must skip it."""
    if not train_dir:
      self._log("fault corrupt_ckpt: no train_dir; nothing to corrupt")
      return
    # Local import: this module stays importable without the package
    # (the hazard lint loads files standalone); checkpoint imports jax.
    from kf_benchmarks_tpu import checkpoint
    ckpts = checkpoint.all_checkpoints(train_dir)
    if not ckpts:
      self._log("fault corrupt_ckpt: no checkpoint on disk yet")
      return
    _, fname = ckpts[-1]
    path = os.path.join(train_dir, fname)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
      f.truncate(max(1, size // 2))
    self._log(f"fault corrupt_ckpt: truncated {fname} "
              f"{size} -> {max(1, size // 2)} bytes")
