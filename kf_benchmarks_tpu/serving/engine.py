"""Request-driven serving engine: continuous batching + admission control.

Host-side half of the serving path (device programs: decode.py). The
reference never had a request path at all (its serving story is the
frozen forward-only loop, ref: benchmark_cnn.py:2405-2525); this engine
turns request ARRIVALS into device throughput:

* **Bounded executable set** -- decode/prefill programs exist only at
  bucket-ladder batch widths (default 1/4/16/64/256), AOT-compiled via
  ``jit(...).lower(...).compile()`` once per bucket and cached keyed on
  ``analysis/baseline.config_fingerprint_key``; every compile lands in
  the run-trace compile ledger, which is how the e2e test pins
  "<= len(ladder) decode compiles across a mixed-length replay"
  (tests/test_serving.py).
* **Continuous in-flight batching** -- freed slots refill from the
  queue every decode step (``batching='continuous'``); the A/B arm
  ``'static'`` is classic batch-and-drain: admit a wave, decode it to
  completion, only then admit again (experiments/serving_sweep.py
  measures the p99-TTFT gap between the two at fixed offered load).
* **SLO-aware admission** -- queue-depth rejection at submit,
  TTFT-deadline expiry at coalesce time, and a per-tenant token-bucket
  budget; rejected/expired requests are first-class results and
  ``serving/*`` metrics, never exceptions.
* **Decode-cost variants** (ISSUE 16; composable, all spec-driven) --
  ``spec.quantize='int8'`` serves per-channel INT8 weights dequantized
  inside the compiled step; ``spec.kv_page_size`` runs the paged KV
  pool with this engine as the page ALLOCATOR (pages granted for a
  request's whole lifetime at prefill, freed at completion, pool
  exhaustion requeues the wave remainder -- a shed path, never an
  exception); ``spec.speculative_k`` runs draft-propose/target-verify
  rounds where the engine's step loop drives the DRAFT model and the
  target is consulted once per round through a prefill-shaped verify
  program (greedy output stays token-identical to plain greedy decode
  -- every emitted token is the target verifier's own argmax).
* **Observability joins** -- request spans (enqueue -> coalesce ->
  prefill -> decode -> done) land on the active ``RunTrace`` timeline
  ("serving" lane); TTFT / per-token latency ride ``add_sample`` into
  the standard percentile machinery; counters/gauges go through the
  registered ``serving/*`` schema keys (metrics.py). Decode-step
  device time is attributed from completion-to-completion intervals
  (the token fetch is a value dependency) -- never
  ``jax.block_until_ready`` (utils/sync.py).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kf_benchmarks_tpu import metrics as metrics_lib
from kf_benchmarks_tpu import quantization
from kf_benchmarks_tpu import tracing as tracing_lib
from kf_benchmarks_tpu.serving import decode as decode_lib

DEFAULT_BUCKET_LADDER = (1, 4, 16, 64, 256)


def bucket_for(n: int, ladder: Sequence[int]) -> int:
  """Smallest ladder bucket >= n (the top bucket when n overflows)."""
  for b in ladder:
    if n <= b:
      return b
  return ladder[-1]


@dataclasses.dataclass(frozen=True)
class EngineConfig:
  spec: decode_lib.LMSpec = dataclasses.field(
      default_factory=decode_lib.LMSpec)
  bucket_ladder: Tuple[int, ...] = DEFAULT_BUCKET_LADDER
  batching: str = "continuous"       # or "static" (batch-and-drain)
  max_new_tokens: int = 32           # default per-request cap
  max_queue_depth: int = 64          # submit-time rejection bound
  ttft_slo_s: Optional[float] = None  # default TTFT deadline (expiry)
  tenant_tokens_per_s: Optional[float] = None  # None = unmetered
  tenant_burst_s: float = 4.0        # token-bucket burst window
  # Error-budget burn-rate monitoring (metrics.SLOMonitor): objectives
  # are ttft_deadline (first token within its deadline) and
  # shed_fraction (request admitted at all); target = good fraction.
  slo_target: float = 0.99
  slo_fast_window_s: float = 15.0
  slo_slow_window_s: float = 60.0
  slo_burn_threshold: float = 2.0

  def __post_init__(self):
    ladder = tuple(sorted(set(int(b) for b in self.bucket_ladder)))
    if not ladder or ladder[0] < 1:
      raise ValueError(f"bucket ladder must be positive ints, got "
                       f"{self.bucket_ladder}")
    object.__setattr__(self, "bucket_ladder", ladder)
    if self.batching not in ("continuous", "static"):
      raise ValueError(f"batching must be 'continuous' or 'static', "
                       f"got {self.batching!r}")

  def fingerprint_config(self, bucket: int, program: str) -> dict:
    """The executable-cache / compile-ledger key payload: the served
    model's shape plus the one shape knob (the bucket)."""
    return {**self.spec.config(), "bucket": int(bucket),
            "serving_program": program}


@dataclasses.dataclass
class Request:
  rid: Any
  prompt: Any                         # 1-D int32 token array
  max_new_tokens: Optional[int] = None
  tenant: str = "default"
  deadline_s: Optional[float] = None  # TTFT deadline (engine default
                                      # applies when None)
  enqueue_t: Optional[float] = None   # stamped by submit()


@dataclasses.dataclass
class RequestResult:
  rid: Any
  tenant: str
  status: str                         # ok | rejected | expired
  tokens: List[int] = dataclasses.field(default_factory=list)
  ttft_s: Optional[float] = None
  total_s: Optional[float] = None
  shed_reason: Optional[str] = None


class ServingEngine:
  """One-process serving loop over the decode.py programs.

  Synchronous by design: callers drive it with ``submit`` + ``drain``
  (tests) or ``replay(workload)`` (bench/sweep -- wall-clock arrival
  offsets). TPU discipline: ONE engine per process, programs dispatched
  strictly serially, results awaited by value dependency.
  """

  def __init__(self, config: EngineConfig, variables=None,
               seed: int = 0, time_fn=time.monotonic,
               sleep_fn=time.sleep, draft_variables=None,
               recorder=None):
    self.cfg = config
    self.spec = config.spec
    self._time = time_fn
    self._sleep = sleep_fn
    raw = (variables if variables is not None
           else decode_lib.init_variables(self.spec, seed))
    self.variables = decode_lib.prepare_variables(self.spec, raw)
    # Speculative mode: the step loop (decode/prefill programs, the KV
    # cache) runs the DRAFT; the target owns only the verify program.
    # _step_spec/_step_vars are what every per-step codepath uses, so
    # the non-speculative engine is the degenerate draft == target.
    if self.spec.speculative_k:
      self._draft = decode_lib.draft_spec(self.spec)
      if draft_variables is None:
        # Self-drafting default: the draft is the target's own first
        # draft_n_layers (truncate_variables) -- a free draft whose
        # early-layer features track the target far better than a
        # random init ever would. Token identity holds for ANY draft;
        # only the acceptance rate (and so the speedup) depends on it.
        base = raw
        if quantization.has_quantized_leaves(base):
          base = quantization.dequantize_variables(base,
                                                   self.spec.param_dtype)
        draft_variables = decode_lib.truncate_variables(self.spec, base)
      self.draft_variables = decode_lib.prepare_variables(
          self._draft, draft_variables)
      self._step_spec = self._draft
      self._step_vars = self.draft_variables
    else:
      self._draft = None
      self.draft_variables = None
      self._step_spec = self.spec
      self._step_vars = self.variables
    self._queue: collections.deque = collections.deque()
    self._results: Dict[Any, RequestResult] = {}
    self._order: List[Any] = []
    self._bucket = 0
    self._cache: Optional[decode_lib.CacheState] = None
    self._slots: List[Optional[dict]] = []
    self._decode_exes: Dict[int, Any] = {}
    self._prefill_exes: Dict[int, Any] = {}
    self._verify_exes: Dict[int, Any] = {}
    # Paged-KV allocator state (spec.kv_page_size): the authoritative
    # per-slot page tables are HOST numpy (scheduler metadata, shipped
    # to each step as an argument); pool row 0 is the scratch page.
    self._pps = self._step_spec.pages_per_slot
    self._free_pages: List[int] = []
    self._table_np = (np.zeros((0, self._pps), np.int32)
                      if self._pps else None)
    self._kv_pages_peak = 0
    self._kv_fraction_peak = 0.0
    self._arrivals = 0
    self._shed = 0
    self._completed = 0
    self._decode_steps = 0
    self._tokens_out = 0
    self._fill_sum = 0.0
    self._queue_depth_sum = 0.0
    self._ticks = 0
    self._ttfts: List[float] = []
    self._token_lat: List[float] = []
    self._spec_rounds = 0
    self._draft_tokens = 0
    self._accepted_tokens = 0
    self._accept_lens: List[float] = []
    self._tenant_allowance: Dict[str, float] = {}
    self._tenant_last: Dict[str, float] = {}
    # Per-tenant observability (round 21): every tenant the engine has
    # seen gets its own TTFT/token-latency samples, token counts, and
    # shed-by-reason counters -- the labeled half of the serving/*
    # schema keys.
    self._tenant_ttfts: Dict[str, List[float]] = {}
    self._tenant_token_lat: Dict[str, List[float]] = {}
    self._tenant_tokens: Dict[str, int] = {}
    self._tenant_arrivals: Dict[str, int] = {}
    self._tenant_completed: Dict[str, int] = {}
    self._tenant_shed: Dict[Tuple[str, str], int] = {}
    # Burn-rate monitor over the two serving objectives; alert
    # episodes land on the flight recorder (when attached) and on
    # /healthz -- data, never exceptions, like the sheds themselves.
    self.slo = metrics_lib.SLOMonitor(
        objectives={"ttft_deadline": config.slo_target,
                    "shed_fraction": config.slo_target},
        fast_window_s=config.slo_fast_window_s,
        slow_window_s=config.slo_slow_window_s,
        burn_threshold=config.slo_burn_threshold,
        time_fn=time_fn, recorder=recorder)
    self._t_serve0: Optional[float] = None
    self._t_serve1: Optional[float] = None
    self._last_step_t: Optional[float] = None
    self.state = "idle"

  # -- admission --------------------------------------------------------------

  def submit(self, req: Request) -> bool:
    """Enqueue one request; returns False when admission shed it
    (queue depth / tenant budget) -- the shed is a RESULT, not an
    exception. A pre-stamped ``enqueue_t`` is honored (replay stamps
    the SCHEDULED arrival time, so TTFT and deadline expiry include
    any wait behind an in-flight decode step -- the coordinated-
    omission trap); direct callers get stamped here."""
    now = self._time()
    if req.enqueue_t is None:
      req.enqueue_t = now
    self._arrivals += 1
    tenant = req.tenant
    self._tenant_arrivals[tenant] = \
        self._tenant_arrivals.get(tenant, 0) + 1
    reg = metrics_lib.active()
    reg.inc("serving/requests")
    reg.inc("serving/requests", labels={"tenant": tenant})
    if len(self._queue) >= self.cfg.max_queue_depth:
      self._shed_request(req, "queue_depth")
      return False
    prompt_len = int(np.asarray(req.prompt).size)
    if prompt_len < 1:
      self._shed_request(req, "empty_prompt")
      return False
    if prompt_len > self.spec.max_len:
      self._shed_request(req, "prompt_too_long")
      return False
    if self.spec.speculative_k and (
        prompt_len + self._max_new(req) + self.spec.speculative_k
        > self.spec.max_len):
      # Verify rows are history ++ proposals laid out flat in a
      # (B, max_len) token batch -- no ring wrap exists for them, so
      # the whole lifetime must fit the context up front.
      self._shed_request(req, "prompt_too_long")
      return False
    tokens = prompt_len + self._max_new(req)
    if not self._tenant_admit(req.tenant, tokens, now):
      self._shed_request(req, "tenant_budget")
      return False
    tracing_lib.active().instant("serving", "enqueue", rid=str(req.rid),
                                 tenant=req.tenant)
    self._queue.append(req)
    return True

  def _max_new(self, req: Request) -> int:
    return int(req.max_new_tokens or self.cfg.max_new_tokens)

  def _deadline(self, req: Request) -> Optional[float]:
    return (req.deadline_s if req.deadline_s is not None
            else self.cfg.ttft_slo_s)

  def _tenant_admit(self, tenant: str, tokens: int, now: float) -> bool:
    rate = self.cfg.tenant_tokens_per_s
    if rate is None:
      return True
    burst = rate * self.cfg.tenant_burst_s
    allowance = self._tenant_allowance.get(tenant, burst)
    last = self._tenant_last.get(tenant, now)
    allowance = min(burst, allowance + (now - last) * rate)
    self._tenant_last[tenant] = now
    if tokens > allowance:
      self._tenant_allowance[tenant] = allowance
      return False
    self._tenant_allowance[tenant] = allowance - tokens
    return True

  def _shed_request(self, req: Request, reason: str,
                    status: str = "rejected") -> None:
    self._shed += 1
    tenant = req.tenant
    self._tenant_shed[(tenant, reason)] = \
        self._tenant_shed.get((tenant, reason), 0) + 1
    reg = metrics_lib.active()
    reg.inc("serving/shed")
    reg.inc("serving/shed", labels={"tenant": tenant,
                                    "shed_reason": reason})
    # A shed is a bad event on the shed-fraction objective; it also
    # burns the TTFT objective when the request carried a deadline (it
    # will never see a first token).
    self.slo.observe("shed_fraction", tenant, good=False)
    if self._deadline(req) is not None:
      self.slo.observe("ttft_deadline", tenant, good=False)
    self._publish_slo(tenant)
    tracing_lib.active().instant("serving", "shed", rid=str(req.rid),
                                 reason=reason)
    self._record(RequestResult(rid=req.rid, tenant=req.tenant,
                               status=status, shed_reason=reason))

  _SLO_BURN_KEYS = {
      "ttft_deadline": ("serving/slo_ttft_burn_fast",
                        "serving/slo_ttft_burn_slow"),
      "shed_fraction": ("serving/slo_shed_burn_fast",
                        "serving/slo_shed_burn_slow"),
  }

  def _publish_slo(self, tenant: str) -> None:
    """Publish this tenant's current burn rates as labeled gauges (the
    live half; stats() republishes the final values at drain)."""
    reg = metrics_lib.active()
    for objective, (fast_key, slow_key) in self._SLO_BURN_KEYS.items():
      burns = self.slo.burn(objective, tenant)
      if burns["fast"] is not None:
        reg.set(fast_key, burns["fast"], labels={"tenant": tenant})
      if burns["slow"] is not None:
        reg.set(slow_key, burns["slow"], labels={"tenant": tenant})

  def _note_first_token(self, req: Request, now: float) -> float:
    """First-token bookkeeping shared by the plain prefill path and
    the first speculative verify round: global + per-tenant TTFT
    samples, the labeled TTFT histogram, and the ttft_deadline SLO
    event (good iff the first token beat the request's deadline)."""
    ttft = now - req.enqueue_t
    tenant = req.tenant
    self._ttfts.append(ttft)
    self._tenant_ttfts.setdefault(tenant, []).append(ttft)
    tracing_lib.active().add_sample("serving/ttft", ttft)
    metrics_lib.active().observe("serving/ttft_s", ttft,
                                 labels={"tenant": tenant})
    deadline = self._deadline(req)
    if deadline is not None:
      self.slo.observe("ttft_deadline", tenant, good=ttft <= deadline)
      self._publish_slo(tenant)
    return ttft

  def _record(self, result: RequestResult) -> None:
    if result.rid not in self._results:
      self._order.append(result.rid)
    self._results[result.rid] = result

  # -- executable cache (the bounded set) -------------------------------------

  def _compile(self, kind: str, bucket: int, fn, abstract_args,
               donate, spec=None) -> Any:
    from kf_benchmarks_tpu.analysis import baseline as baseline_lib
    key = baseline_lib.config_fingerprint_key(
        self.cfg.fingerprint_config(bucket, kind), program=kind)
    t0 = time.monotonic()
    # The shared AOT recipe (decode.aot_jit): donation always, and the
    # tensor-parallel NamedShardings when spec.model_shards is set.
    compiled = decode_lib.aot_jit(spec or self._step_spec, fn, kind,
                                  bucket, donate).lower(
        *abstract_args).compile()
    tracing_lib.active().note_compile(key, kind,
                                      time.monotonic() - t0,
                                      bucket=bucket)
    return compiled

  def _decode_exe(self, bucket: int):
    if bucket not in self._decode_exes:
      fn, args, donate = decode_lib.decode_lowering_args(
          self._step_spec, bucket)
      self._decode_exes[bucket] = self._compile(
          "serving_decode", bucket, fn, args, donate=donate)
    return self._decode_exes[bucket]

  def _prefill_exe(self, bucket: int):
    # Keyed on the PACK bucket (the wave size), independent of the
    # decode bucket: a one-request refill wave pays a one-row packed
    # forward even while a wide decode batch is in flight.
    if bucket not in self._prefill_exes:
      import jax
      spec = self._step_spec
      var_sds = decode_lib.abstract_variables(spec)
      i32 = lambda: jax.ShapeDtypeStruct((bucket,), np.int32)
      args = (var_sds,
              jax.ShapeDtypeStruct((bucket, 3, spec.max_len), np.int32),
              i32(), i32(), i32())
      self._prefill_exes[bucket] = self._compile(
          "serving_prefill", bucket, decode_lib.prefill_fn(spec), args,
          donate=())
    return self._prefill_exes[bucket]

  def _verify_exe(self, bucket: int):
    # The speculative TARGET's one program: the full spec (not the
    # draft), undonated, keyed per decode bucket like the others --
    # the bounded-compile ledger e2e counts serving_verify as its own
    # <= len(ladder) family.
    if bucket not in self._verify_exes:
      fn, args, donate = decode_lib.verify_lowering_args(self.spec,
                                                         bucket)
      self._verify_exes[bucket] = self._compile(
          "serving_verify", bucket, fn, args, donate=donate,
          spec=self.spec)
    return self._verify_exes[bucket]

  def warm(self, buckets: Optional[Sequence[int]] = None) -> int:
    """Precompile the decode + prefill (+ verify, when speculative)
    executables for ``buckets`` (default: the whole ladder) BEFORE
    serving -- the `analysis warm` discipline applied to the request
    path, so the first wave's TTFT measures the system, not XLA.
    Returns the number of executables compiled."""
    n = 0
    for b in (buckets if buckets is not None else self.cfg.bucket_ladder):
      b = bucket_for(int(b), self.cfg.bucket_ladder)
      before = (len(self._decode_exes) + len(self._prefill_exes)
                + len(self._verify_exes))
      self._decode_exe(b)
      self._prefill_exe(b)
      if self.spec.speculative_k:
        self._verify_exe(b)
      n += (len(self._decode_exes) + len(self._prefill_exes)
            + len(self._verify_exes) - before)
    return n

  # -- the serving loop -------------------------------------------------------

  def _active_count(self) -> int:
    return sum(1 for s in self._slots if s is not None)

  def _ensure_bucket(self, target: int) -> None:
    want = bucket_for(target, self.cfg.bucket_ladder)
    if want <= self._bucket:
      return
    old_pool = (self._cache.k.shape[1] if self._cache is not None
                else 0)
    if self._cache is None:
      self._cache = decode_lib.init_cache(self._step_spec, want)
    else:
      self._cache = decode_lib.grow_cache(self._cache, self._step_spec,
                                          want)
    if self._pps:
      # Pool growth keeps old page ids valid (grow_cache copies the
      # pool prefix); only the NEW rows join the free list.
      new_pool = self._cache.k.shape[1]
      if old_pool == 0:
        self._free_pages = list(range(1, new_pool))
        self._table_np = np.zeros((want, self._pps), np.int32)
      else:
        self._free_pages.extend(range(old_pool, new_pool))
        grown = np.zeros((want, self._pps), np.int32)
        grown[:self._table_np.shape[0]] = self._table_np
        self._table_np = grown
    self._slots.extend([None] * (want - self._bucket))
    self._bucket = want
    metrics_lib.active().set("serving/decode_bucket", want)

  def _maybe_shrink(self) -> None:
    """Compact the decode batch DOWN the ladder when occupancy drops:
    a decode step costs ~O(bucket) host/device work, so dragging a
    wide bucket at low fill taxes every remaining token (measured on
    the CPU-mesh A/B). Active slots compact to the front; an empty
    engine drops its cache entirely so the next wave sizes itself.
    The ladder's spacing is the hysteresis -- a shrink only fires when
    occupancy fits a strictly lower bucket."""
    if self._bucket == 0:
      return
    active_idx = [i for i, s in enumerate(self._slots) if s is not None]
    if not active_idx:
      self._bucket = 0
      self._cache = None
      self._slots = []
      if self._pps:
        self._free_pages = []
        self._table_np = np.zeros((0, self._pps), np.int32)
      metrics_lib.active().set("serving/decode_bucket", 0)
      return
    target = bucket_for(len(active_idx), self.cfg.bucket_ladder)
    if target >= self._bucket:
      return
    import jax.numpy as jnp
    keep = jnp.asarray(
        active_idx + [0] * (target - len(active_idx)), jnp.int32)
    cache = self._cache
    if self._pps:
      # Paged shrink = page-pool compaction: live pages (in kept-slot
      # order) remap onto the head of the smaller pool; slot tables
      # rewrite to the new ids. Skip when the live set does not fit
      # the target pool (a long-session tail can exceed the smaller
      # pool's KV_POOL_FRACTION budget -- the ladder retries next
      # tick once completions free pages).
      new_pool = decode_lib.kv_pool_pages(self._step_spec, target)
      live = [int(pid) for i in active_idx
              for pid in self._table_np[i] if pid]
      if 1 + len(live) > new_pool:
        return
      old_of = np.zeros((new_pool,), np.int32)   # new id -> old id
      remap = {0: 0}
      for new_id, pid in enumerate(live, start=1):
        remap[pid] = new_id
        old_of[new_id] = pid
      gather = jnp.asarray(old_of)
      table = np.zeros((target, self._pps), np.int32)
      for row, i in enumerate(active_idx):
        table[row] = [remap.get(int(pid), 0) for pid in self._table_np[i]]
        # The slot's free-at-completion list must follow the remap, or
        # _complete would return the OLD ids to the new pool.
        self._slots[i]["pages"] = [remap[p]
                                   for p in self._slots[i]["pages"]]
      self._table_np = table
      self._free_pages = list(range(1 + len(live), new_pool))
      self._cache = decode_lib.CacheState(
          k=cache.k[:, gather], v=cache.v[:, gather],
          pos=cache.pos[keep], tok=cache.tok[keep])
    else:
      # Pad rows duplicate slot 0's cache; they carry active=False, so
      # their contents are never read and their writes land on the pad
      # row only.
      self._cache = decode_lib.CacheState(
          k=cache.k[:, keep], v=cache.v[:, keep],
          pos=cache.pos[keep], tok=cache.tok[keep])
    self._slots = ([self._slots[i] for i in active_idx]
                   + [None] * (target - len(active_idx)))
    self._bucket = target
    metrics_lib.active().set("serving/decode_bucket", target)

  def _coalesce(self, now: float) -> List[Request]:
    """Pop admitted work for this wave: expired requests shed here
    (deadline-based shedding), live ones admitted up to the ladder
    headroom left by in-flight slots."""
    headroom = self.cfg.bucket_ladder[-1] - self._active_count()
    wave: List[Request] = []
    while self._queue and len(wave) < headroom:
      req = self._queue.popleft()
      deadline = self._deadline(req)
      if deadline is not None and now - req.enqueue_t > deadline:
        self._shed_request(req, "ttft_deadline", status="expired")
        continue
      wave.append(req)
    return wave

  def _pages_needed(self, prompt_len: int, max_new: int) -> int:
    """Pages a request needs for its WHOLE lifetime (prompt + budget
    + speculative lookahead) -- allocated once at prefill, so decode
    never grows mid-flight. Capped at pages_per_slot: a fully
    allocated slot has the dense slab's ring semantics exactly
    (positions wrap inside its own pages)."""
    page = self._step_spec.kv_page_size
    need = prompt_len + max_new + self.spec.speculative_k
    return min(self._pps, -(-need // page))

  def _prefill_wave(self, wave: List[Request]) -> None:
    from kf_benchmarks_tpu.data import packing as packing_lib
    import jax.numpy as jnp
    self._ensure_bucket(self._active_count() + len(wave))
    free = [i for i, s in enumerate(self._slots) if s is None]
    # Pack bucket = the wave's own ladder size (rows <= prompts always
    # suffice: every prompt fits one row).
    pack_bucket = bucket_for(len(wave), self.cfg.bucket_ladder)
    prompts = [np.asarray(r.prompt, np.int32) for r in wave]
    packed_np, placements = packing_lib.pack_prompts(
        prompts, self.spec.max_len, pack_bucket)
    placed: List[Tuple[Request, np.ndarray, Tuple[int, int], int]] = []
    overflow: List[Request] = []
    avail_pages = len(self._free_pages) if self._pps else 0
    for req, prm, place in zip(wave, prompts, placements):
      need = (self._pages_needed(prm.size, self._max_new(req))
              if self._pps else 0)
      if (place is None or len(placed) >= min(len(free), pack_bucket)
          or need > avail_pages):
        # Pool exhaustion lands here too: the request requeues and
        # retries after in-flight completions free pages -- a shed
        # path (TTFT-deadline expiry at the next coalesce if an SLO
        # is set), never an exception. An EMPTY engine can never
        # exhaust (kv_pool_pages floors at pages_per_slot + 1 and an
        # idle engine resets to a fresh pool), so requeueing always
        # makes progress.
        overflow.append(req)
      else:
        avail_pages -= need
        placed.append((req, prm, place, need))
    # Requests that did not fit this wave's packed batch go back to
    # the queue HEAD in order (near-FIFO, like the packer's lookahead).
    for req in reversed(overflow):
      self._queue.appendleft(req)
    if not placed:
      return
    r = len(placed)
    rows = np.zeros((pack_bucket,), np.int32)
    offsets = np.zeros((pack_bucket,), np.int32)
    last_pos = np.zeros((pack_bucket,), np.int32)
    lengths = np.zeros((pack_bucket,), np.int32)
    slots = np.full((pack_bucket,), self._bucket, np.int32)  # pad drops
    page_lists: List[List[int]] = []
    if self._pps:
      pool = self._cache.k.shape[1]
      # Sentinel P on unallocated pages / pad rows: the install
      # scatter drops them (mode="drop"); the engine-side table keeps
      # 0 (the scratch page) there instead.
      sent = np.full((pack_bucket, self._pps), pool, np.int32)
    for i, (req, prm, (row, off), need) in enumerate(placed):
      rows[i], offsets[i] = row, off
      lengths[i] = prm.size
      last_pos[i] = off + prm.size - 1
      slots[i] = free[i]
      if self._pps:
        pages = [self._free_pages.pop() for _ in range(need)]
        page_lists.append(pages)
        self._table_np[free[i], :] = 0
        self._table_np[free[i], :need] = pages
        sent[i, :need] = pages
    if self._pps:
      in_use = self._cache.k.shape[1] - 1 - len(self._free_pages)
      self._kv_pages_peak = max(self._kv_pages_peak, in_use)
      self._kv_fraction_peak = max(
          self._kv_fraction_peak,
          in_use / max(self._cache.k.shape[1] - 1, 1))
      reg = metrics_lib.active()
      reg.set("serving/kv_pages_in_use", in_use)
      reg.set("serving/kv_page_fraction",
              in_use / max(self._cache.k.shape[1] - 1, 1))
    exe = self._prefill_exe(pack_bucket)
    trace = tracing_lib.active()
    with trace.span("serving", "prefill", requests=r,
                    bucket=pack_bucket):
      first, ek, ev = exe(*decode_lib.place_serving_args(
          self._step_spec, "serving_prefill", pack_bucket,
          (self._step_vars, jnp.asarray(packed_np), jnp.asarray(rows),
           jnp.asarray(last_pos), jnp.asarray(offsets))))
      if self._pps:
        self._cache = decode_lib.install_prefill_paged(
            self._cache, ek, ev, first, jnp.asarray(lengths),
            jnp.asarray(slots), jnp.asarray(sent))
      else:
        self._cache = decode_lib.install_prefill(
            self._cache, ek, ev, first, jnp.asarray(lengths),
            jnp.asarray(slots))
      first_np = np.asarray(first)  # value dependency = completion
    now = self._time()
    for i, (req, prm, _place, _need) in enumerate(placed):
      if self.spec.speculative_k:
        # Speculative: the prefill ran the DRAFT, so its first token
        # is a PROPOSAL, not an emission -- TTFT and the first real
        # token come from the first verify round.
        slot = {"req": req, "tokens": [], "history": prm.copy(),
                "props": [int(first_np[i])],
                "t_first": None, "ttft": None}
      else:
        ttft = self._note_first_token(req, now)
        slot = {"req": req, "tokens": [int(first_np[i])],
                "t_first": now, "ttft": ttft}
      if self._pps:
        slot["pages"] = page_lists[i]
      self._slots[free[i]] = slot
      if not self.spec.speculative_k:
        if len(slot["tokens"]) >= self._max_new(req):
          self._complete(free[i], now)
        self._tokens_out += 1

  def _run_decode_exe(self, active_np) -> np.ndarray:
    """One batched decode dispatch on the step model (the draft, when
    speculative); updates the cache in place and returns the sampled
    tokens. Shared by the plain decode step and the speculative
    draft-propose loop."""
    import jax.numpy as jnp
    exe = self._decode_exe(self._bucket)
    cache = self._cache
    if self._pps:
      args = (self._step_vars, cache.k, cache.v, cache.pos, cache.tok,
              jnp.asarray(self._table_np), jnp.asarray(active_np))
    else:
      args = (self._step_vars, cache.k, cache.v, cache.pos, cache.tok,
              jnp.asarray(active_np))
    nxt, k, v, pos = exe(*decode_lib.place_serving_args(
        self._step_spec, "serving_decode", self._bucket, args))
    nxt_np = np.asarray(nxt)  # value dependency = completion
    self._cache = decode_lib.CacheState(k=k, v=v, pos=pos,
                                        tok=jnp.asarray(nxt))
    self._decode_steps += 1
    reg = metrics_lib.active()
    reg.inc("serving/decode_steps")
    reg.inc("serving/decode_steps", labels={"bucket": str(self._bucket)})
    return nxt_np

  def _decode_step(self) -> None:
    bucket = self._bucket
    active_np = np.array([s is not None for s in self._slots], np.bool_)
    trace = tracing_lib.active()
    t0 = self._time()
    with trace.span("serving", "decode_step",
                    active=int(active_np.sum()), bucket=bucket):
      nxt_np = self._run_decode_exe(active_np)
    now = self._time()
    step_wall = now - t0
    self._last_step_t = now
    n_active = int(active_np.sum())
    self._fill_sum += n_active / max(bucket, 1)
    self._tokens_out += n_active
    trace.add_sample("serving/token_latency", step_wall)
    self._token_lat.append(step_wall)
    reg = metrics_lib.active()
    reg.observe("serving/token_latency_s", step_wall)
    reg.set("serving/active", n_active)
    for i, slot in enumerate(self._slots):
      if slot is None:
        continue
      slot["tokens"].append(int(nxt_np[i]))
      if len(slot["tokens"]) >= self._max_new(slot["req"]):
        self._complete(i, now)

  def _speculative_round(self) -> None:
    """One draft-propose / target-verify round.

    k-1 draft decode steps extend every active slot's proposal run
    (slots fresh from prefill already hold the draft's first proposal,
    so they offer k; slots continuing from a previous round offer
    k-1). ONE target verify dispatch then scores every slot's row =
    confirmed history ++ proposals, and the engine accepts the longest
    agreeing prefix plus the verifier's own next token (the bonus) --
    so every emitted token is the TARGET's greedy argmax and the
    output is token-identical to plain greedy decode, whatever the
    draft proposed.

    Acceptance is capped at len(proposals)-1 so the accepted prefix
    (whose K/V the draft wrote while proposing) plus the bonus
    position (overwritten by the next draft step) never leaves a
    confirmed position without draft K/V; the cap costs at most the
    bonus-vs-final-proposal token, which the bonus replaces 1:1."""
    import jax.numpy as jnp
    trace = tracing_lib.active()
    t0 = self._time()
    bucket = self._bucket
    active_np = np.array([s is not None for s in self._slots], np.bool_)
    n_active = int(active_np.sum())
    for _ in range(self.spec.speculative_k - 1):
      nxt_np = self._run_decode_exe(active_np)
      for i, slot in enumerate(self._slots):
        if slot is not None:
          slot["props"].append(int(nxt_np[i]))
    rows_np = np.zeros((bucket, self.spec.max_len), np.int32)
    for i, slot in enumerate(self._slots):
      if slot is None:
        continue
      row = np.concatenate(
          [slot["history"], np.asarray(slot["props"], np.int32)])
      rows_np[i, :row.size] = row
    exe = self._verify_exe(bucket)
    with trace.span("serving", "verify", active=n_active,
                    bucket=bucket):
      preds = np.asarray(exe(*decode_lib.place_serving_args(
          self.spec, "serving_verify", bucket,
          (self.variables, jnp.asarray(rows_np)))))
    now = self._time()
    self._spec_rounds += 1
    self._last_step_t = now
    self._fill_sum += n_active / max(bucket, 1)
    reg = metrics_lib.active()
    reg.inc("serving/spec_rounds")
    reg.set("serving/active", n_active)
    new_pos = np.array(self._cache.pos)
    new_tok = np.array(self._cache.tok)
    emitted_total = 0
    round_draft = round_accepted = 0
    for i, slot in enumerate(list(self._slots)):
      if slot is None:
        continue
      history, props = slot["history"], slot["props"]
      q0 = history.size
      # props[j] sits at row position q0+j; the target's greedy choice
      # FOR that position is preds[i, q0+j-1] (preds[t] predicts t+1).
      agree = 0
      while (agree < len(props)
             and props[agree] == preds[i, q0 + agree - 1]):
        agree += 1
      a = min(agree, len(props) - 1)
      bonus = int(preds[i, q0 + a - 1])
      emit = [int(x) for x in props[:a]] + [bonus]
      room = self._max_new(slot["req"]) - len(slot["tokens"])
      emit = emit[:room]
      round_draft += len(props)
      round_accepted += min(a, len(emit))
      self._accept_lens.append(float(min(a, len(emit))))
      trace.add_sample("serving/accept_len", float(min(a, len(emit))))
      reg.observe("serving/accept_len", float(min(a, len(emit))))
      slot["tokens"].extend(emit)
      slot["history"] = np.concatenate(
          [history, np.asarray(emit, np.int32)])
      slot["props"] = []
      # Rewind the draft cache onto the confirmed row: the new tok is
      # the last emitted token at position len(history')-1; the next
      # draft step writes its K/V there (overwriting whatever rejected
      # proposal K/V the draft had left).
      new_pos[i] = slot["history"].size - 1
      new_tok[i] = emit[-1]
      if slot["t_first"] is None:
        slot["t_first"] = now
        slot["ttft"] = self._note_first_token(slot["req"], now)
      emitted_total += len(emit)
      if len(slot["tokens"]) >= self._max_new(slot["req"]):
        self._complete(i, now)
    self._draft_tokens += round_draft
    self._accepted_tokens += round_accepted
    reg.inc("serving/draft_tokens", round_draft)
    reg.inc("serving/accepted_tokens", round_accepted)
    self._cache = decode_lib.CacheState(
        k=self._cache.k, v=self._cache.v,
        pos=jnp.asarray(new_pos), tok=jnp.asarray(new_tok))
    self._tokens_out += emitted_total
    per_tok = (now - t0) / max(emitted_total, 1)
    self._token_lat.append(per_tok)
    trace.add_sample("serving/token_latency", per_tok)
    reg.observe("serving/token_latency_s", per_tok)

  def _complete(self, slot_idx: int, now: float) -> None:
    slot = self._slots[slot_idx]
    self._slots[slot_idx] = None
    if self._pps:
      # Free the slot's pages and point its table row at the scratch
      # page: a freed slot's (inactive) decode writes land on scratch,
      # never on a page a later request owns.
      self._free_pages.extend(slot["pages"])
      self._table_np[slot_idx, :] = 0
    req = slot["req"]
    tenant = req.tenant
    self._completed += 1
    self._tenant_completed[tenant] = \
        self._tenant_completed.get(tenant, 0) + 1
    self._tenant_tokens[tenant] = \
        self._tenant_tokens.get(tenant, 0) + len(slot["tokens"])
    reg = metrics_lib.active()
    reg.inc("serving/completed")
    reg.inc("serving/completed", labels={"tenant": tenant})
    result = RequestResult(
        rid=req.rid, tenant=req.tenant, status="ok",
        tokens=list(slot["tokens"]), ttft_s=slot["ttft"],
        total_s=now - req.enqueue_t)
    # Per-tenant token latency: the request's own mean decode interval
    # (total wall after the first token over the tokens it bought) --
    # a per-REQUEST figure, so a tenant's percentiles reflect its own
    # requests rather than whichever batch it shared.
    if len(result.tokens) > 1 and result.ttft_s is not None:
      per_tok = (result.total_s - result.ttft_s) / (len(result.tokens)
                                                    - 1)
      self._tenant_token_lat.setdefault(tenant, []).append(per_tok)
      reg.observe("serving/token_latency_s", per_tok,
                  labels={"tenant": tenant})
    # A completion is a good event on the shed-fraction objective.
    self.slo.observe("shed_fraction", tenant, good=True)
    self._publish_slo(tenant)
    self._record(result)
    trace = tracing_lib.active()
    # Retrospective whole-request span: enqueue -> completion, on the
    # trace clock (requests were stamped with self._time; translate by
    # the shared monotonic origin only when the clocks coincide).
    trace.add_span("serving", "request", trace.now() - result.total_s,
                   result.total_s,
                   {"rid": str(req.rid), "status": "ok",
                    "ttft_s": round(result.ttft_s, 6),
                    "tokens": len(result.tokens)})

  def _tick(self) -> None:
    self._ticks += 1
    reg = metrics_lib.active()
    self._queue_depth_sum += len(self._queue)
    reg.set("serving/queue_depth", len(self._queue))
    self._maybe_shrink()
    now = self._time()
    admit = bool(self._queue) and (
        self.cfg.batching == "continuous" or self._active_count() == 0)
    if admit:
      wave = self._coalesce(now)
      if wave:
        self._prefill_wave(wave)
    if self._active_count():
      if self.spec.speculative_k:
        self._speculative_round()
      else:
        self._decode_step()

  def drain(self) -> List[RequestResult]:
    """Serve until queue and slots are empty; returns every result so
    far in submission order."""
    self.state = "running"
    if self._t_serve0 is None:
      self._t_serve0 = self._time()
    while self._queue or self._active_count():
      self._tick()
    self._t_serve1 = self._time()
    self.state = "drained"
    self._publish()
    return self.results()

  def replay(self, workload: Sequence[Tuple[float, Request]]
             ) -> List[RequestResult]:
    """Replay a seeded workload of (arrival_offset_s, request) pairs in
    wall time: requests become visible at their offsets, the loop
    decodes continuously in between (idle gaps sleep until the next
    arrival). The replayable-trace form bench.py --serving and
    experiments/serving_sweep.py drive."""
    self.state = "running"
    pending = collections.deque(
        sorted(workload, key=lambda pair: pair[0]))
    start = self._time()
    self._t_serve0 = start
    while pending or self._queue or self._active_count():
      now = self._time() - start
      while pending and pending[0][0] <= now:
        offset, req = pending.popleft()
        # The SCHEDULED arrival is the enqueue time: a request whose
        # offset fell while a decode step was in flight has already
        # been waiting, and its TTFT/deadline clock must say so.
        req.enqueue_t = start + offset
        self.submit(req)
      if not self._queue and not self._active_count() and pending:
        self._sleep(max(0.0, pending[0][0] - (self._time() - start)))
        continue
      self._tick()
    self._t_serve1 = self._time()
    self.state = "drained"
    self._publish()
    return self.results()

  def results(self) -> List[RequestResult]:
    return [self._results[rid] for rid in self._order]

  # -- reporting --------------------------------------------------------------

  def healthz(self) -> Dict[str, Any]:
    """Engine liveness for the /healthz endpoint (metrics.py
    MetricsServer healthz_fn). Status distinguishes "up" from "up but
    burning error budget": any firing SLO stream turns it
    "burning"."""
    slo = self.slo.state()
    return {
        "status": slo["status"] if slo["status"] != "ok" else "ok",
        "serving": {
            "state": self.state,
            "active": self._active_count(),
            "queue_depth": len(self._queue),
            "bucket": self._bucket,
            "completed": self._completed,
            "shed": self._shed,
            "decode_steps": self._decode_steps,
        },
        "slo": slo,
    }

  def serve_metrics(self, port: int, registry=None,
                    host: str = "127.0.0.1"):
    """Bind the live /metrics + /healthz endpoint for this engine."""
    return metrics_lib.MetricsServer(
        registry if registry is not None else metrics_lib.active(),
        port, host=host, healthz_fn=self.healthz)

  def stats(self) -> Dict[str, Any]:
    """Flat registered-key stats of the run so far (the bench.py
    --serving JSON payload; every key lives in metrics.SCHEMA)."""
    wall = None
    if self._t_serve0 is not None and self._t_serve1 is not None:
      wall = max(self._t_serve1 - self._t_serve0, 1e-9)
    pct = tracing_lib.percentile
    out = {
        "serving/requests": self._arrivals,
        "serving/completed": self._completed,
        "serving/shed": self._shed,
        "serving/shed_fraction": (self._shed / self._arrivals
                                  if self._arrivals else 0.0),
        "serving/decode_steps": self._decode_steps,
        "serving/decode_bucket": self._bucket,
        "serving/batch_fill_fraction": (
            self._fill_sum / self._decode_steps
            if self._decode_steps else None),
        "serving/queue_depth": (self._queue_depth_sum / self._ticks
                                if self._ticks else None),
        "serving/tokens_per_sec": (self._tokens_out / wall
                                   if wall else None),
        "serving/ttft_p50": pct(self._ttfts, 50),
        "serving/ttft_p90": pct(self._ttfts, 90),
        "serving/ttft_p99": pct(self._ttfts, 99),
        "serving/token_latency_p50": pct(self._token_lat, 50),
        "serving/token_latency_p90": pct(self._token_lat, 90),
        "serving/token_latency_p99": pct(self._token_lat, 99),
        # Variant stats: None when the variant is off (the publish
        # path drops None, so variant-off runs report exactly the
        # pre-variant key set).
        "serving/kv_pages_in_use": (self._kv_pages_peak
                                    if self._pps else None),
        "serving/kv_page_fraction": (self._kv_fraction_peak
                                     if self._pps else None),
        "serving/spec_rounds": (self._spec_rounds
                                if self.spec.speculative_k else None),
        "serving/draft_tokens": (self._draft_tokens
                                 if self.spec.speculative_k else None),
        "serving/accepted_tokens": (
            self._accepted_tokens if self.spec.speculative_k else None),
        "serving/accept_len_p50": (
            pct(self._accept_lens, 50)
            if self.spec.speculative_k else None),
        "serving/accept_len_p90": (
            pct(self._accept_lens, 90)
            if self.spec.speculative_k else None),
        "serving/accept_len_p99": (
            pct(self._accept_lens, 99)
            if self.spec.speculative_k else None),
        "serving/slo_alerts": float(len(self.slo.alerts)),
        # Aggregate burn = the worst tenant (the number an unlabeled
        # dashboard should alarm on); None before any SLO event.
        "serving/slo_ttft_burn_fast": self._agg_burn("ttft_deadline",
                                                     "fast"),
        "serving/slo_ttft_burn_slow": self._agg_burn("ttft_deadline",
                                                     "slow"),
        "serving/slo_shed_burn_fast": self._agg_burn("shed_fraction",
                                                     "fast"),
        "serving/slo_shed_burn_slow": self._agg_burn("shed_fraction",
                                                     "slow"),
        # Per-tenant block: flatten_stats expands it onto labeled keys
        # (name{tenant=...}; sheds additionally carry shed_reason).
        "serving_tenants": self.tenant_stats(),
    }
    return out

  def _agg_burn(self, objective: str, window: str) -> Optional[float]:
    burns = [self.slo.burn(objective, t)[window]
             for t in self._tenants_seen()]
    burns = [b for b in burns if b is not None]
    return max(burns) if burns else None

  def _tenants_seen(self) -> List[str]:
    seen = set(self._tenant_arrivals)
    seen.update(t for (t, _r) in self._tenant_shed)
    return sorted(seen)

  def tenant_stats(self) -> Dict[str, Dict[str, Any]]:
    """Per-tenant stats keyed on FULL registered metric names (so the
    flattened labeled keys stay inside the single-source schema)."""
    pct = tracing_lib.percentile
    wall = None
    if self._t_serve0 is not None and self._t_serve1 is not None:
      wall = max(self._t_serve1 - self._t_serve0, 1e-9)
    out: Dict[str, Dict[str, Any]] = {}
    for tenant in self._tenants_seen():
      ttfts = self._tenant_ttfts.get(tenant, [])
      lats = self._tenant_token_lat.get(tenant, [])
      sheds = {reason: n for (t, reason), n in
               sorted(self._tenant_shed.items()) if t == tenant}
      ttft_burn = self.slo.burn("ttft_deadline", tenant)
      shed_burn = self.slo.burn("shed_fraction", tenant)
      out[tenant] = {
          "serving/requests": self._tenant_arrivals.get(tenant, 0),
          "serving/completed": self._tenant_completed.get(tenant, 0),
          "serving/shed": sheds or None,
          "serving/tokens_per_sec": (
              self._tenant_tokens.get(tenant, 0) / wall
              if wall else None),
          "serving/ttft_p50": pct(ttfts, 50),
          "serving/ttft_p90": pct(ttfts, 90),
          "serving/ttft_p99": pct(ttfts, 99),
          "serving/token_latency_p50": pct(lats, 50),
          "serving/token_latency_p90": pct(lats, 90),
          "serving/token_latency_p99": pct(lats, 99),
          "serving/slo_ttft_burn_fast": ttft_burn["fast"],
          "serving/slo_ttft_burn_slow": ttft_burn["slow"],
          "serving/slo_shed_burn_fast": shed_burn["fast"],
          "serving/slo_shed_burn_slow": shed_burn["slow"],
      }
    return out

  def _publish(self) -> None:
    reg = metrics_lib.active()
    for key, value in metrics_lib.flatten_stats(self.stats()).items():
      base, labels = metrics_lib.parse_labeled_key(key)
      kind = metrics_lib.SCHEMA[base].kind
      if kind in ("counter", "histogram"):
        continue  # counters/histograms were published live
      reg.set(base, value, labels=labels or None)


# -- replayable workloads -----------------------------------------------------

def poisson_workload(n: int, rate_per_s: float, spec: decode_lib.LMSpec,
                     seed: int = 0, max_new_tokens: int = 16,
                     mean_prompt_fraction: float = 0.2,
                     tenants: Sequence[str] = ("default",)
                     ) -> List[Tuple[float, Request]]:
  """A seeded, replayable open-loop arrival trace: exponential
  inter-arrivals at ``rate_per_s``, lognormal prompt lengths
  (data/packing.py's document-length shape, scaled down so prompts +
  generation fit the ring), tenants round-robin. Same seed => same
  workload, the A/B and regression-comparison contract."""
  from kf_benchmarks_tpu.data import packing as packing_lib
  rng = np.random.default_rng(seed)
  # Speculative specs need prompt + max_new + k to fit the context
  # (verify rows never wrap), so the prompt cap shrinks by k.
  cap = max(1, spec.max_len - max_new_tokens - spec.speculative_k - 1)
  lengths = np.minimum(
      packing_lib.sample_document_lengths(
          rng, n, spec.max_len, mean_fraction=mean_prompt_fraction),
      cap)
  gaps = rng.exponential(1.0 / max(rate_per_s, 1e-9), size=n)
  t = np.cumsum(gaps)
  out = []
  for i in range(n):
    prompt = rng.integers(0, spec.vocab, size=int(lengths[i]),
                          dtype=np.int32)
    out.append((float(t[i]), Request(
        rid=i, prompt=prompt, max_new_tokens=max_new_tokens,
        tenant=tenants[i % len(tenants)])))
  return out
