"""Request-driven serving engine: continuous batching + admission control.

Host-side half of the serving path (device programs: decode.py). The
reference never had a request path at all (its serving story is the
frozen forward-only loop, ref: benchmark_cnn.py:2405-2525); this engine
turns request ARRIVALS into device throughput:

* **Bounded executable set** -- decode/prefill programs exist only at
  bucket-ladder batch widths (default 1/4/16/64/256), AOT-compiled via
  ``jit(...).lower(...).compile()`` once per bucket and cached keyed on
  ``analysis/baseline.config_fingerprint_key``; every compile lands in
  the run-trace compile ledger, which is how the e2e test pins
  "<= len(ladder) decode compiles across a mixed-length replay"
  (tests/test_serving.py).
* **Continuous in-flight batching** -- freed slots refill from the
  queue every decode step (``batching='continuous'``); the A/B arm
  ``'static'`` is classic batch-and-drain: admit a wave, decode it to
  completion, only then admit again (experiments/serving_sweep.py
  measures the p99-TTFT gap between the two at fixed offered load).
* **SLO-aware admission** -- queue-depth rejection at submit,
  TTFT-deadline expiry at coalesce time, and a per-tenant token-bucket
  budget; rejected/expired requests are first-class results and
  ``serving/*`` metrics, never exceptions.
* **Observability joins** -- request spans (enqueue -> coalesce ->
  prefill -> decode -> done) land on the active ``RunTrace`` timeline
  ("serving" lane); TTFT / per-token latency ride ``add_sample`` into
  the standard percentile machinery; counters/gauges go through the
  registered ``serving/*`` schema keys (metrics.py). Decode-step
  device time is attributed from completion-to-completion intervals
  (the token fetch is a value dependency) -- never
  ``jax.block_until_ready`` (utils/sync.py).
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kf_benchmarks_tpu import metrics as metrics_lib
from kf_benchmarks_tpu import tracing as tracing_lib
from kf_benchmarks_tpu.serving import decode as decode_lib

DEFAULT_BUCKET_LADDER = (1, 4, 16, 64, 256)


def bucket_for(n: int, ladder: Sequence[int]) -> int:
  """Smallest ladder bucket >= n (the top bucket when n overflows)."""
  for b in ladder:
    if n <= b:
      return b
  return ladder[-1]


@dataclasses.dataclass(frozen=True)
class EngineConfig:
  spec: decode_lib.LMSpec = dataclasses.field(
      default_factory=decode_lib.LMSpec)
  bucket_ladder: Tuple[int, ...] = DEFAULT_BUCKET_LADDER
  batching: str = "continuous"       # or "static" (batch-and-drain)
  max_new_tokens: int = 32           # default per-request cap
  max_queue_depth: int = 64          # submit-time rejection bound
  ttft_slo_s: Optional[float] = None  # default TTFT deadline (expiry)
  tenant_tokens_per_s: Optional[float] = None  # None = unmetered
  tenant_burst_s: float = 4.0        # token-bucket burst window

  def __post_init__(self):
    ladder = tuple(sorted(set(int(b) for b in self.bucket_ladder)))
    if not ladder or ladder[0] < 1:
      raise ValueError(f"bucket ladder must be positive ints, got "
                       f"{self.bucket_ladder}")
    object.__setattr__(self, "bucket_ladder", ladder)
    if self.batching not in ("continuous", "static"):
      raise ValueError(f"batching must be 'continuous' or 'static', "
                       f"got {self.batching!r}")

  def fingerprint_config(self, bucket: int, program: str) -> dict:
    """The executable-cache / compile-ledger key payload: the served
    model's shape plus the one shape knob (the bucket)."""
    return {**self.spec.config(), "bucket": int(bucket),
            "serving_program": program}


@dataclasses.dataclass
class Request:
  rid: Any
  prompt: Any                         # 1-D int32 token array
  max_new_tokens: Optional[int] = None
  tenant: str = "default"
  deadline_s: Optional[float] = None  # TTFT deadline (engine default
                                      # applies when None)
  enqueue_t: Optional[float] = None   # stamped by submit()


@dataclasses.dataclass
class RequestResult:
  rid: Any
  tenant: str
  status: str                         # ok | rejected | expired
  tokens: List[int] = dataclasses.field(default_factory=list)
  ttft_s: Optional[float] = None
  total_s: Optional[float] = None
  shed_reason: Optional[str] = None


class ServingEngine:
  """One-process serving loop over the decode.py programs.

  Synchronous by design: callers drive it with ``submit`` + ``drain``
  (tests) or ``replay(workload)`` (bench/sweep -- wall-clock arrival
  offsets). TPU discipline: ONE engine per process, programs dispatched
  strictly serially, results awaited by value dependency.
  """

  def __init__(self, config: EngineConfig, variables=None,
               seed: int = 0, time_fn=time.monotonic,
               sleep_fn=time.sleep):
    self.cfg = config
    self.spec = config.spec
    self._time = time_fn
    self._sleep = sleep_fn
    self.variables = (variables if variables is not None
                      else decode_lib.init_variables(self.spec, seed))
    self._queue: collections.deque = collections.deque()
    self._results: Dict[Any, RequestResult] = {}
    self._order: List[Any] = []
    self._bucket = 0
    self._cache: Optional[decode_lib.CacheState] = None
    self._slots: List[Optional[dict]] = []
    self._decode_exes: Dict[int, Any] = {}
    self._prefill_exes: Dict[int, Any] = {}
    self._arrivals = 0
    self._shed = 0
    self._completed = 0
    self._decode_steps = 0
    self._tokens_out = 0
    self._fill_sum = 0.0
    self._queue_depth_sum = 0.0
    self._ticks = 0
    self._ttfts: List[float] = []
    self._token_lat: List[float] = []
    self._tenant_allowance: Dict[str, float] = {}
    self._tenant_last: Dict[str, float] = {}
    self._t_serve0: Optional[float] = None
    self._t_serve1: Optional[float] = None
    self._last_step_t: Optional[float] = None
    self.state = "idle"

  # -- admission --------------------------------------------------------------

  def submit(self, req: Request) -> bool:
    """Enqueue one request; returns False when admission shed it
    (queue depth / tenant budget) -- the shed is a RESULT, not an
    exception. A pre-stamped ``enqueue_t`` is honored (replay stamps
    the SCHEDULED arrival time, so TTFT and deadline expiry include
    any wait behind an in-flight decode step -- the coordinated-
    omission trap); direct callers get stamped here."""
    now = self._time()
    if req.enqueue_t is None:
      req.enqueue_t = now
    self._arrivals += 1
    reg = metrics_lib.active()
    reg.inc("serving/requests")
    if len(self._queue) >= self.cfg.max_queue_depth:
      self._shed_request(req, "queue_depth")
      return False
    prompt_len = int(np.asarray(req.prompt).size)
    if prompt_len < 1:
      self._shed_request(req, "empty_prompt")
      return False
    if prompt_len > self.spec.max_len:
      self._shed_request(req, "prompt_too_long")
      return False
    tokens = prompt_len + self._max_new(req)
    if not self._tenant_admit(req.tenant, tokens, now):
      self._shed_request(req, "tenant_budget")
      return False
    tracing_lib.active().instant("serving", "enqueue", rid=str(req.rid),
                                 tenant=req.tenant)
    self._queue.append(req)
    return True

  def _max_new(self, req: Request) -> int:
    return int(req.max_new_tokens or self.cfg.max_new_tokens)

  def _deadline(self, req: Request) -> Optional[float]:
    return (req.deadline_s if req.deadline_s is not None
            else self.cfg.ttft_slo_s)

  def _tenant_admit(self, tenant: str, tokens: int, now: float) -> bool:
    rate = self.cfg.tenant_tokens_per_s
    if rate is None:
      return True
    burst = rate * self.cfg.tenant_burst_s
    allowance = self._tenant_allowance.get(tenant, burst)
    last = self._tenant_last.get(tenant, now)
    allowance = min(burst, allowance + (now - last) * rate)
    self._tenant_last[tenant] = now
    if tokens > allowance:
      self._tenant_allowance[tenant] = allowance
      return False
    self._tenant_allowance[tenant] = allowance - tokens
    return True

  def _shed_request(self, req: Request, reason: str,
                    status: str = "rejected") -> None:
    self._shed += 1
    reg = metrics_lib.active()
    reg.inc("serving/shed")
    tracing_lib.active().instant("serving", "shed", rid=str(req.rid),
                                 reason=reason)
    self._record(RequestResult(rid=req.rid, tenant=req.tenant,
                               status=status, shed_reason=reason))

  def _record(self, result: RequestResult) -> None:
    if result.rid not in self._results:
      self._order.append(result.rid)
    self._results[result.rid] = result

  # -- executable cache (the bounded set) -------------------------------------

  def _compile(self, kind: str, bucket: int, fn, abstract_args,
               donate) -> Any:
    from kf_benchmarks_tpu.analysis import baseline as baseline_lib
    import jax
    key = baseline_lib.config_fingerprint_key(
        self.cfg.fingerprint_config(bucket, kind), program=kind)
    t0 = time.monotonic()
    compiled = jax.jit(fn, donate_argnums=donate).lower(
        *abstract_args).compile()
    tracing_lib.active().note_compile(key, kind,
                                      time.monotonic() - t0,
                                      bucket=bucket)
    return compiled

  def _decode_exe(self, bucket: int):
    if bucket not in self._decode_exes:
      fn, args, donate = decode_lib.decode_lowering_args(self.spec,
                                                         bucket)
      self._decode_exes[bucket] = self._compile(
          "serving_decode", bucket, fn, args, donate=donate)
    return self._decode_exes[bucket]

  def _prefill_exe(self, bucket: int):
    # Keyed on the PACK bucket (the wave size), independent of the
    # decode bucket: a one-request refill wave pays a one-row packed
    # forward even while a wide decode batch is in flight.
    if bucket not in self._prefill_exes:
      import jax
      spec = self.spec
      var_sds = decode_lib.abstract_variables(spec)
      i32 = lambda: jax.ShapeDtypeStruct((bucket,), np.int32)
      args = (var_sds,
              jax.ShapeDtypeStruct((bucket, 3, spec.max_len), np.int32),
              i32(), i32(), i32())
      self._prefill_exes[bucket] = self._compile(
          "serving_prefill", bucket, decode_lib.prefill_fn(spec), args,
          donate=())
    return self._prefill_exes[bucket]

  def warm(self, buckets: Optional[Sequence[int]] = None) -> int:
    """Precompile the decode + prefill executables for ``buckets``
    (default: the whole ladder) BEFORE serving -- the `analysis warm`
    discipline applied to the request path, so the first wave's TTFT
    measures the system, not XLA. Returns the number of executables
    compiled."""
    n = 0
    for b in (buckets if buckets is not None else self.cfg.bucket_ladder):
      b = bucket_for(int(b), self.cfg.bucket_ladder)
      before = len(self._decode_exes) + len(self._prefill_exes)
      self._decode_exe(b)
      self._prefill_exe(b)
      n += len(self._decode_exes) + len(self._prefill_exes) - before
    return n

  # -- the serving loop -------------------------------------------------------

  def _active_count(self) -> int:
    return sum(1 for s in self._slots if s is not None)

  def _ensure_bucket(self, target: int) -> None:
    want = bucket_for(target, self.cfg.bucket_ladder)
    if want <= self._bucket:
      return
    if self._cache is None:
      self._cache = decode_lib.init_cache(self.spec, want)
    else:
      self._cache = decode_lib.grow_cache(self._cache, self.spec, want)
    self._slots.extend([None] * (want - self._bucket))
    self._bucket = want
    metrics_lib.active().set("serving/decode_bucket", want)

  def _maybe_shrink(self) -> None:
    """Compact the decode batch DOWN the ladder when occupancy drops:
    a decode step costs ~O(bucket) host/device work, so dragging a
    wide bucket at low fill taxes every remaining token (measured on
    the CPU-mesh A/B). Active slots compact to the front; an empty
    engine drops its cache entirely so the next wave sizes itself.
    The ladder's spacing is the hysteresis -- a shrink only fires when
    occupancy fits a strictly lower bucket."""
    if self._bucket == 0:
      return
    active_idx = [i for i, s in enumerate(self._slots) if s is not None]
    if not active_idx:
      self._bucket = 0
      self._cache = None
      self._slots = []
      metrics_lib.active().set("serving/decode_bucket", 0)
      return
    target = bucket_for(len(active_idx), self.cfg.bucket_ladder)
    if target >= self._bucket:
      return
    import jax.numpy as jnp
    # Pad rows duplicate slot 0's cache; they carry active=False, so
    # their contents are never read and their writes land on the pad
    # row only.
    keep = jnp.asarray(
        active_idx + [0] * (target - len(active_idx)), jnp.int32)
    cache = self._cache
    self._cache = decode_lib.CacheState(
        k=cache.k[:, keep], v=cache.v[:, keep],
        pos=cache.pos[keep], tok=cache.tok[keep])
    self._slots = ([self._slots[i] for i in active_idx]
                   + [None] * (target - len(active_idx)))
    self._bucket = target
    metrics_lib.active().set("serving/decode_bucket", target)

  def _coalesce(self, now: float) -> List[Request]:
    """Pop admitted work for this wave: expired requests shed here
    (deadline-based shedding), live ones admitted up to the ladder
    headroom left by in-flight slots."""
    headroom = self.cfg.bucket_ladder[-1] - self._active_count()
    wave: List[Request] = []
    while self._queue and len(wave) < headroom:
      req = self._queue.popleft()
      deadline = self._deadline(req)
      if deadline is not None and now - req.enqueue_t > deadline:
        self._shed_request(req, "ttft_deadline", status="expired")
        continue
      wave.append(req)
    return wave

  def _prefill_wave(self, wave: List[Request]) -> None:
    from kf_benchmarks_tpu.data import packing as packing_lib
    import jax.numpy as jnp
    self._ensure_bucket(self._active_count() + len(wave))
    free = [i for i, s in enumerate(self._slots) if s is None]
    # Pack bucket = the wave's own ladder size (rows <= prompts always
    # suffice: every prompt fits one row).
    pack_bucket = bucket_for(len(wave), self.cfg.bucket_ladder)
    prompts = [np.asarray(r.prompt, np.int32) for r in wave]
    packed_np, placements = packing_lib.pack_prompts(
        prompts, self.spec.max_len, pack_bucket)
    placed: List[Tuple[Request, np.ndarray, Tuple[int, int]]] = []
    overflow: List[Request] = []
    for req, prm, place in zip(wave, prompts, placements):
      if place is None or len(placed) >= min(len(free), pack_bucket):
        overflow.append(req)
      else:
        placed.append((req, prm, place))
    # Requests that did not fit this wave's packed batch go back to
    # the queue HEAD in order (near-FIFO, like the packer's lookahead).
    for req in reversed(overflow):
      self._queue.appendleft(req)
    if not placed:
      return
    r = len(placed)
    rows = np.zeros((pack_bucket,), np.int32)
    offsets = np.zeros((pack_bucket,), np.int32)
    last_pos = np.zeros((pack_bucket,), np.int32)
    lengths = np.zeros((pack_bucket,), np.int32)
    slots = np.full((pack_bucket,), self._bucket, np.int32)  # pad drops
    for i, (req, prm, (row, off)) in enumerate(placed):
      rows[i], offsets[i] = row, off
      lengths[i] = prm.size
      last_pos[i] = off + prm.size - 1
      slots[i] = free[i]
    exe = self._prefill_exe(pack_bucket)
    trace = tracing_lib.active()
    with trace.span("serving", "prefill", requests=r,
                    bucket=pack_bucket):
      first, ek, ev = exe(self.variables, jnp.asarray(packed_np),
                          jnp.asarray(rows), jnp.asarray(last_pos),
                          jnp.asarray(offsets))
      self._cache = decode_lib.install_prefill(
          self._cache, ek, ev, first, jnp.asarray(lengths),
          jnp.asarray(slots))
      first_np = np.asarray(first)  # value dependency = completion
    now = self._time()
    for i, (req, prm, _place) in enumerate(placed):
      ttft = now - req.enqueue_t
      self._ttfts.append(ttft)
      trace.add_sample("serving/ttft", ttft)
      slot = {"req": req, "tokens": [int(first_np[i])],
              "t_first": now, "ttft": ttft}
      self._slots[free[i]] = slot
      if len(slot["tokens"]) >= self._max_new(req):
        self._complete(free[i], now)
    self._tokens_out += r

  def _decode_step(self) -> None:
    import jax.numpy as jnp
    bucket = self._bucket
    active_np = np.array([s is not None for s in self._slots], np.bool_)
    exe = self._decode_exe(bucket)
    cache = self._cache
    trace = tracing_lib.active()
    t0 = self._time()
    with trace.span("serving", "decode_step",
                    active=int(active_np.sum()), bucket=bucket):
      nxt, k, v, pos = exe(self.variables, cache.k, cache.v, cache.pos,
                           cache.tok, jnp.asarray(active_np))
      nxt_np = np.asarray(nxt)  # value dependency = completion
    now = self._time()
    step_wall = now - t0
    self._cache = decode_lib.CacheState(k=k, v=v, pos=pos,
                                        tok=jnp.asarray(nxt))
    self._decode_steps += 1
    self._last_step_t = now
    n_active = int(active_np.sum())
    self._fill_sum += n_active / max(bucket, 1)
    self._tokens_out += n_active
    trace.add_sample("serving/token_latency", step_wall)
    self._token_lat.append(step_wall)
    reg = metrics_lib.active()
    reg.inc("serving/decode_steps")
    reg.set("serving/active", n_active)
    for i, slot in enumerate(self._slots):
      if slot is None:
        continue
      slot["tokens"].append(int(nxt_np[i]))
      if len(slot["tokens"]) >= self._max_new(slot["req"]):
        self._complete(i, now)

  def _complete(self, slot_idx: int, now: float) -> None:
    slot = self._slots[slot_idx]
    self._slots[slot_idx] = None
    req = slot["req"]
    self._completed += 1
    metrics_lib.active().inc("serving/completed")
    result = RequestResult(
        rid=req.rid, tenant=req.tenant, status="ok",
        tokens=list(slot["tokens"]), ttft_s=slot["ttft"],
        total_s=now - req.enqueue_t)
    self._record(result)
    trace = tracing_lib.active()
    # Retrospective whole-request span: enqueue -> completion, on the
    # trace clock (requests were stamped with self._time; translate by
    # the shared monotonic origin only when the clocks coincide).
    trace.add_span("serving", "request", trace.now() - result.total_s,
                   result.total_s,
                   {"rid": str(req.rid), "status": "ok",
                    "ttft_s": round(result.ttft_s, 6),
                    "tokens": len(result.tokens)})

  def _tick(self) -> None:
    self._ticks += 1
    reg = metrics_lib.active()
    self._queue_depth_sum += len(self._queue)
    reg.set("serving/queue_depth", len(self._queue))
    self._maybe_shrink()
    now = self._time()
    admit = bool(self._queue) and (
        self.cfg.batching == "continuous" or self._active_count() == 0)
    if admit:
      wave = self._coalesce(now)
      if wave:
        self._prefill_wave(wave)
    if self._active_count():
      self._decode_step()

  def drain(self) -> List[RequestResult]:
    """Serve until queue and slots are empty; returns every result so
    far in submission order."""
    self.state = "running"
    if self._t_serve0 is None:
      self._t_serve0 = self._time()
    while self._queue or self._active_count():
      self._tick()
    self._t_serve1 = self._time()
    self.state = "drained"
    self._publish()
    return self.results()

  def replay(self, workload: Sequence[Tuple[float, Request]]
             ) -> List[RequestResult]:
    """Replay a seeded workload of (arrival_offset_s, request) pairs in
    wall time: requests become visible at their offsets, the loop
    decodes continuously in between (idle gaps sleep until the next
    arrival). The replayable-trace form bench.py --serving and
    experiments/serving_sweep.py drive."""
    self.state = "running"
    pending = collections.deque(
        sorted(workload, key=lambda pair: pair[0]))
    start = self._time()
    self._t_serve0 = start
    while pending or self._queue or self._active_count():
      now = self._time() - start
      while pending and pending[0][0] <= now:
        offset, req = pending.popleft()
        # The SCHEDULED arrival is the enqueue time: a request whose
        # offset fell while a decode step was in flight has already
        # been waiting, and its TTFT/deadline clock must say so.
        req.enqueue_t = start + offset
        self.submit(req)
      if not self._queue and not self._active_count() and pending:
        self._sleep(max(0.0, pending[0][0] - (self._time() - start)))
        continue
      self._tick()
    self._t_serve1 = self._time()
    self.state = "drained"
    self._publish()
    return self.results()

  def results(self) -> List[RequestResult]:
    return [self._results[rid] for rid in self._order]

  # -- reporting --------------------------------------------------------------

  def healthz(self) -> Dict[str, Any]:
    """Engine liveness for the /healthz endpoint (metrics.py
    MetricsServer healthz_fn)."""
    return {
        "status": "ok",
        "serving": {
            "state": self.state,
            "active": self._active_count(),
            "queue_depth": len(self._queue),
            "bucket": self._bucket,
            "completed": self._completed,
            "shed": self._shed,
            "decode_steps": self._decode_steps,
        },
    }

  def serve_metrics(self, port: int, registry=None,
                    host: str = "127.0.0.1"):
    """Bind the live /metrics + /healthz endpoint for this engine."""
    return metrics_lib.MetricsServer(
        registry if registry is not None else metrics_lib.active(),
        port, host=host, healthz_fn=self.healthz)

  def stats(self) -> Dict[str, Any]:
    """Flat registered-key stats of the run so far (the bench.py
    --serving JSON payload; every key lives in metrics.SCHEMA)."""
    wall = None
    if self._t_serve0 is not None and self._t_serve1 is not None:
      wall = max(self._t_serve1 - self._t_serve0, 1e-9)
    pct = tracing_lib.percentile
    out = {
        "serving/requests": self._arrivals,
        "serving/completed": self._completed,
        "serving/shed": self._shed,
        "serving/shed_fraction": (self._shed / self._arrivals
                                  if self._arrivals else 0.0),
        "serving/decode_steps": self._decode_steps,
        "serving/decode_bucket": self._bucket,
        "serving/batch_fill_fraction": (
            self._fill_sum / self._decode_steps
            if self._decode_steps else None),
        "serving/queue_depth": (self._queue_depth_sum / self._ticks
                                if self._ticks else None),
        "serving/tokens_per_sec": (self._tokens_out / wall
                                   if wall else None),
        "serving/ttft_p50": pct(self._ttfts, 50),
        "serving/ttft_p90": pct(self._ttfts, 90),
        "serving/ttft_p99": pct(self._ttfts, 99),
        "serving/token_latency_p50": pct(self._token_lat, 50),
        "serving/token_latency_p90": pct(self._token_lat, 90),
        "serving/token_latency_p99": pct(self._token_lat, 99),
    }
    return out

  def _publish(self) -> None:
    reg = metrics_lib.active()
    for key, value in self.stats().items():
      if value is None:
        continue
      if metrics_lib.SCHEMA[key].kind == "counter":
        continue  # counters were incremented live
      reg.set(key, value)


# -- replayable workloads -----------------------------------------------------

def poisson_workload(n: int, rate_per_s: float, spec: decode_lib.LMSpec,
                     seed: int = 0, max_new_tokens: int = 16,
                     mean_prompt_fraction: float = 0.2,
                     tenants: Sequence[str] = ("default",)
                     ) -> List[Tuple[float, Request]]:
  """A seeded, replayable open-loop arrival trace: exponential
  inter-arrivals at ``rate_per_s``, lognormal prompt lengths
  (data/packing.py's document-length shape, scaled down so prompts +
  generation fit the ring), tenants round-robin. Same seed => same
  workload, the A/B and regression-comparison contract."""
  from kf_benchmarks_tpu.data import packing as packing_lib
  rng = np.random.default_rng(seed)
  cap = max(1, spec.max_len - max_new_tokens - 1)
  lengths = np.minimum(
      packing_lib.sample_document_lengths(
          rng, n, spec.max_len, mean_fraction=mean_prompt_fraction),
      cap)
  gaps = rng.exponential(1.0 / max(rate_per_s, 1e-9), size=n)
  t = np.cumsum(gaps)
  out = []
  for i in range(n):
    prompt = rng.integers(0, spec.vocab, size=int(lengths[i]),
                          dtype=np.int32)
    out.append((float(t[i]), Request(
        rid=i, prompt=prompt, max_new_tokens=max_new_tokens,
        tenant=tenants[i % len(tenants)])))
  return out
