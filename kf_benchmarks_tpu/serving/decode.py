"""KV-cache LM decode programs for the serving engine.

Device-side half of the serving path (ref: the reference's closest
analog is the frozen forward-only loop, benchmark_cnn.py:2405-2525;
everything autoregressive here is beyond-reference). Three programs,
each compiled ahead of time per bucket by the engine:

* **prefill** -- mixed-length prompts, first-fit packed into one
  ``(B_pack, 3, T)`` stack (data/packing.py ``pack_prompts``), run
  through the full-sequence forward with ``return_kv=True``: one
  dispatch produces every prompt's first sampled token (from the fused
  head's hidden states -- no (B, T, V) logits tensor ever exists) AND
  its per-layer K/V span, which is sliced out of the packed rows and
  installed into the ring-buffer cache slots in the same program.
* **decode step** -- one token per active slot through the
  ``decode=True`` transformer_lm path: write K/V into the ring at
  ``pos``, attend over ``slot <= pos``, greedy-sample the next token
  in-program. Caches are donated, so the step updates them in place --
  the executable's only traffic is the (B,) token/pos vectors.
* **cache state** -- the explicit ``(L, B, T, H, Dh)`` K/V ring
  buffers plus per-slot ``pos``/``tok`` vectors; per-slot positions are
  what lets continuous batching refill one freed slot while its
  neighbors keep decoding.

Numerical contract (tests/test_serving.py): with ``decode_exact=True``
the per-token f32 logits of the incremental path are BIT-IDENTICAL to
the full-sequence forward at every prefix length, for both the
blockwise CPU schedule and the flash path's CPU reference (the masked
positions contribute exactly zero; the exact mode reuses the full
forward's op graph -- see sequence.decode_attention). The fast 1-row
schedule agrees to float rounding (~2e-6 measured) and is the
production default.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kf_benchmarks_tpu import quantization
from kf_benchmarks_tpu.models import transformer_lm as lm

# Paged-KV pool sizing: the pool provisions this fraction of the dense
# slab's pages (bucket x pages_per_slot) plus the scratch page --
# HALF the dense ceiling, because the workload's lognormal prompt
# lengths (data/packing.py) put typical occupancy far below worst-case
# T_max, which is the whole point of paging: the budget scales with
# actual tokens. Floored at one full sequence + scratch so a
# max-length request always fits an empty pool.
KV_POOL_FRACTION = 0.5

# The target verifier computes greedy argmax CHUNK-wise over the
# sequence (max_len/8 positions of logits at a time, gcd-clamped so the
# chunk divides max_len) -- the fused-head discipline applied to
# verification: no (B, T, V) logits tensor ever exists in the verify
# program (audit rule serving-verify-bounded).
VERIFY_CHUNK_DENOM = 8


@dataclasses.dataclass(frozen=True)
class LMSpec:
  """The served LM's shape -- defaults are the zoo transformer_lm, so
  a serving benchmark exercises the same program family the training
  harness measures. ``max_len`` is both the ring-buffer length and the
  packed-prefill width; prompts + generation beyond it fall into the
  ring's sliding window."""
  vocab: int = lm.VOCAB
  d_model: int = lm.D_MODEL
  n_layers: int = lm.N_LAYERS
  n_heads: int = lm.N_HEADS
  d_ff: int = lm.D_FF
  max_len: int = lm.SEQ_LEN
  attn_block: int = lm.ATTN_BLOCK
  attn_impl: str = "tiled"
  scan_layers: bool = True
  decode_exact: bool = False
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32
  # --- decode-cost variants (ISSUE 16); all default-off, and all emit
  # None into config() when off so config_fingerprint_key drops them
  # and pre-variant fingerprints/goldens stay byte-identical. ---
  # "int8": weight-only per-out-channel INT8 (quantization.py leaves),
  # dequantized INSIDE the compiled step -- the TPU-native analog of
  # the reference's --trt_mode=INT8 (benchmark_cnn.py:453-460).
  quantize: Optional[str] = None
  # >0: paged KV -- (L, P, page, H, Dh) block pool + per-request page
  # tables instead of the dense (L, B, T_max, H, Dh) ring slab.
  kv_page_size: int = 0
  # >0: speculative decoding -- a draft_n_layers-deep draft proposes
  # k tokens per target verify dispatch.
  speculative_k: int = 0
  draft_n_layers: int = 0
  # >=2: tensor-parallel serving (ISSUE 17) -- the decode/prefill/
  # verify executables lower with Megatron-style NamedShardings over a
  # ('model',)-axis mesh of this many devices (attention + MLP kernels
  # column/row-parallel, KV cache sharded on the head axis, everything
  # else replicated; tp_shardings below) and GSPMD inserts the
  # exchange. 0 = single-device programs, byte-identical to before
  # this round (config() emits None so fingerprints don't move).
  model_shards: int = 0

  def __post_init__(self):
    if self.quantize not in (None, "int8"):
      raise ValueError(
          f"quantize must be None or 'int8', got {self.quantize!r}")
    if self.kv_page_size < 0 or (
        self.kv_page_size and self.max_len % self.kv_page_size):
      raise ValueError(
          f"kv_page_size ({self.kv_page_size}) must be positive and "
          f"divide max_len ({self.max_len}): partial pages would break "
          "the page-table <-> ring position bijection")
    if self.speculative_k < 0 or self.draft_n_layers < 0:
      raise ValueError("speculative_k/draft_n_layers must be >= 0")
    if self.speculative_k == 1:
      raise ValueError(
          "speculative_k must be >= 2: one proposal per target verify "
          "is strictly slower than plain decode (a verify dispatch "
          "costs a full forward)")
    if self.speculative_k and not (
        0 < self.draft_n_layers < self.n_layers):
      raise ValueError(
          f"speculative_k={self.speculative_k} requires a draft spec: "
          f"0 < draft_n_layers ({self.draft_n_layers}) < n_layers "
          f"({self.n_layers})")
    if self.draft_n_layers and not self.speculative_k:
      raise ValueError(
          "draft_n_layers without speculative_k is inert -- set both")
    if self.model_shards:
      if self.model_shards < 2:
        raise ValueError(
            "model_shards must be >= 2 (1 is the unsharded program; "
            "ask for 0 instead so fingerprints stay put)")
      if self.n_heads % self.model_shards or \
          self.d_ff % self.model_shards:
        raise ValueError(
            f"model_shards ({self.model_shards}) must divide n_heads "
            f"({self.n_heads}) and d_ff ({self.d_ff}): the shardings "
            "split the head and FF axes evenly")
      if self.quantize:
        raise ValueError(
            "model_shards with quantize is not supported: the INT8 "
            "per-out-channel scale leaves would need their own "
            "resharding rules (untested composition; serve one of "
            "the two)")

  @property
  def head_dim(self) -> int:
    return self.d_model // self.n_heads

  @property
  def pages_per_slot(self) -> int:
    return self.max_len // self.kv_page_size if self.kv_page_size else 0

  def config(self) -> dict:
    """The fingerprint payload (analysis/baseline.config_fingerprint_key
    keys the executable cache and compile ledger on it)."""
    return {
        "vocab": self.vocab, "d_model": self.d_model,
        "n_layers": self.n_layers, "n_heads": self.n_heads,
        "d_ff": self.d_ff, "max_len": self.max_len,
        "attn_block": self.attn_block, "attn_impl": self.attn_impl,
        "scan_layers": self.scan_layers,
        "decode_exact": self.decode_exact,
        "dtype": jnp.dtype(self.dtype).name,
        "param_dtype": jnp.dtype(self.param_dtype).name,
        # None-when-disabled: fingerprints drop None fields, so
        # variant-off configs hash exactly as before this round.
        "quantize": self.quantize,
        "kv_page_size": self.kv_page_size or None,
        "speculative_k": self.speculative_k or None,
        "draft_n_layers": self.draft_n_layers or None,
        "model_shards": self.model_shards or None,
    }


class CacheState(NamedTuple):
  """The explicit ring-buffer decode state. ``k``/``v``:
  (L, B, T, H, Dh) dense, or the shared (L, P, page, H, Dh) block POOL
  when ``spec.kv_page_size`` is set (pool row 0 is the scratch page --
  never allocated, it absorbs unallocated page-table entries); ``pos``:
  (B,) absolute position of each slot's CURRENT token; ``tok``: (B,)
  the token at that position (not yet in the cache -- the next decode
  step writes it). In paged mode the per-slot page tables are HOST
  state (engine._table_np), passed to each step as a (B, pages_per_
  slot) int32 arg -- they are scheduler metadata, not model state."""
  k: Any
  v: Any
  pos: Any
  tok: Any


def _module_kwargs(spec: LMSpec) -> dict:
  return dict(vocab=spec.vocab, d_model=spec.d_model,
              n_layers=spec.n_layers, n_heads=spec.n_heads,
              d_ff=spec.d_ff, attn_block=spec.attn_block,
              attn_q_block=spec.attn_block, attn_impl=spec.attn_impl,
              scan_layers=spec.scan_layers, max_len=spec.max_len,
              dtype=spec.dtype, param_dtype=spec.param_dtype)


def forward_module(spec: LMSpec, fused_head: bool = True,
                   return_kv: bool = False):
  """The full-sequence forward (prefill / oracle reference)."""
  return lm._TransformerLMModule(fused_head=fused_head,
                                 return_kv=return_kv,
                                 **_module_kwargs(spec))


def decode_module(spec: LMSpec):
  """The single-token KV-ring (or paged-pool) decode module."""
  return lm._TransformerLMModule(fused_head=False, decode=True,
                                 decode_exact=spec.decode_exact,
                                 kv_page_size=spec.kv_page_size,
                                 **_module_kwargs(spec))


def draft_spec(spec: LMSpec) -> LMSpec:
  """The speculative draft model's spec: the SAME transformer_lm family
  truncated to ``draft_n_layers`` (identical per-layer params tree
  shape, so a distilled draft checkpoint drops in). Quantize and
  kv_page_size carry over -- the three decode-cost legs compose: the
  engine's step loop (and therefore its caches and compiled decode
  programs) runs the DRAFT when speculative_k is set."""
  if not spec.speculative_k:
    raise ValueError("draft_spec needs speculative_k > 0")
  return dataclasses.replace(spec, n_layers=spec.draft_n_layers,
                             speculative_k=0, draft_n_layers=0)


def truncate_variables(spec: LMSpec, variables):
  """Derive draft weights from the TARGET's by layer truncation: the
  draft keeps the embedding, positional table, final LN and head, plus
  the first ``draft_n_layers`` entries of the scanned block stack --
  the zero-training baseline draft (a distilled checkpoint of the same
  shape drops in wherever this is used). Float trees only; quantize
  AFTER truncation so the draft gets its own per-channel scales."""
  if not spec.speculative_k:
    raise ValueError("truncate_variables needs speculative_k > 0")
  if not spec.scan_layers:
    raise ValueError(
        "truncate_variables slices the scanned block stack; "
        "scan_layers=False lays blocks out as separate modules")
  if quantization.has_quantized_leaves(variables):
    raise ValueError("truncate a float tree, then prepare_variables")
  d = spec.draft_n_layers
  params = variables["params"]
  blocks = jax.tree.map(lambda x: x[:d], params["blocks"])
  new_params = {k: (blocks if k == "blocks" else v)
                for k, v in params.items()}
  return {k: (new_params if k == "params" else v)
          for k, v in variables.items()}


def init_variables(spec: LMSpec, seed: int = 0):
  """Synthetic serving weights (the engine serves frozen weights; any
  checkpointed transformer_lm param tree of the same shape drops in)."""
  module = forward_module(spec, fused_head=True)
  rng = jax.random.PRNGKey(seed)
  sample = jnp.zeros((1, spec.max_len), jnp.int32)
  return module.init({"params": rng, "dropout": rng}, sample)


def abstract_variables(spec: LMSpec):
  """ShapeDtypeStruct variable tree (nothing executes) -- the AOT
  lowering input and the auditor's tracing input. With
  ``spec.quantize`` the abstract tree is the QUANTIZED one ({int8 q,
  f32 per-channel scale} dict leaves on the large kernels), matching
  what the engine actually feeds the compiled programs."""
  module = forward_module(spec, fused_head=True)
  sample = jnp.zeros((1, spec.max_len), jnp.int32)

  def build():
    variables = module.init({"params": jax.random.PRNGKey(0),
                             "dropout": jax.random.PRNGKey(0)}, sample)
    if spec.quantize == "int8":
      variables = quantization.quantize_variables(variables)
    return variables

  return jax.eval_shape(build)


def prepare_variables(spec: LMSpec, variables):
  """Bring a float param tree into the form the spec's compiled
  programs expect: per-channel INT8 leaves when ``spec.quantize``.
  Idempotent -- an already-quantized tree passes through."""
  if spec.quantize == "int8" and not quantization.has_quantized_leaves(
      variables):
    variables = quantization.quantize_variables(variables)
  return variables


def _serving_view(spec: LMSpec, variables):
  """Inside-the-step weight view: dequantize INT8 leaves back to
  ``param_dtype`` so all matmuls see a plain float tree. Traced into
  the compiled step -- the executable's weight inputs stay int8, which
  is the whole HBM-traffic point (~4x fewer weight bytes per
  weight-bound single-token matmul)."""
  if spec.quantize == "int8":
    return quantization.dequantize_variables(variables,
                                             spec.param_dtype)
  return variables


# -- tensor-parallel shardings (ISSUE 17) -------------------------------------

def serving_mesh(spec: LMSpec):
  """The ('model',) tensor-parallel mesh over the first
  ``spec.model_shards`` devices, or None when serving is unsharded."""
  if not spec.model_shards:
    return None
  devices = jax.devices()
  if len(devices) < spec.model_shards:
    raise ValueError(
        f"model_shards={spec.model_shards} needs that many devices; "
        f"have {len(devices)}")
  return jax.sharding.Mesh(np.array(devices[:spec.model_shards]),
                           ("model",))


def _variables_shardings(spec: LMSpec, mesh):
  """Megatron-style NamedShardings for the serving param tree:
  attention qkv and MLP-up kernels column-parallel (last dim),
  attention-out and MLP-down row-parallel (contraction dim), their
  column-parallel biases sharded with the columns, embeddings / LNs /
  head replicated. GSPMD propagates these through the forward and
  inserts one reduction per block where the row-parallel matmuls
  meet -- the hand-derived TP exchange, without hand-writing it."""
  P = jax.sharding.PartitionSpec
  ns = lambda *axes: jax.sharding.NamedSharding(mesh, P(*axes))
  col3 = ns(None, None, "model")   # (L, in, out): split out
  row3 = ns(None, "model", None)   # (L, in, out): split in
  by_name = {
      "qkv": {"kernel": col3},
      "mlp_up": {"kernel": col3, "bias": ns(None, "model")},
      "attn_out": {"kernel": row3},
      "mlp_down": {"kernel": row3},
  }

  def spec_for(path, leaf):
    names = [str(getattr(k, "key", k)) for k in path]
    for mod, fields in by_name.items():
      if mod in names:
        for field, sharding in fields.items():
          if field in names:
            return sharding
    return ns()

  return jax.tree_util.tree_map_with_path(spec_for,
                                          abstract_variables(spec))


def _kv_sharding(spec: LMSpec, mesh, head_axis: int, ndim: int):
  """KV buffers shard on the head axis (dense ring (L, B, T, H, Dh)
  and paged pool (L, P, page, H, Dh) both carry H at index 3; prefill
  extracts at index 3 of (B_pack, L, T, H, Dh) too)."""
  P = jax.sharding.PartitionSpec
  axes = [None] * ndim
  axes[head_axis] = "model"
  return jax.sharding.NamedSharding(mesh, P(*axes))


def tp_shardings(spec: LMSpec, program: str, bucket: int):
  """(in_shardings, out_shardings) for one serving program's jit,
  matching its lowering-args order exactly; (None, None) when the spec
  is unsharded. The engine AND the auditor's serving tracer compile
  through aot_jit below, so the sharded program the golden pins is the
  one the engine caches."""
  mesh = serving_mesh(spec)
  if mesh is None:
    return None, None
  P = jax.sharding.PartitionSpec
  rep = jax.sharding.NamedSharding(mesh, P())
  var_sh = _variables_shardings(spec, mesh)
  if program == "serving_verify":
    return (var_sh, rep), rep
  if program == "serving_prefill":
    ekv = _kv_sharding(spec, mesh, 3, 5)
    return (var_sh, rep, rep, rep, rep), (rep, ekv, ekv)
  kv = _kv_sharding(spec, mesh, 3, 5)
  if spec.kv_page_size:
    ins = (var_sh, kv, kv, rep, rep, rep, rep)
  else:
    ins = (var_sh, kv, kv, rep, rep, rep)
  return ins, (rep, kv, kv, rep)


def aot_jit(spec: LMSpec, fn, program: str, bucket: int, donate):
  """The ONE serving jit recipe: donation always, tensor-parallel
  in/out NamedShardings when ``spec.model_shards`` (tp_shardings).
  Shared by the engine's executable cache and the auditor's tracer."""
  ins, outs = tp_shardings(spec, program, bucket)
  if ins is None:
    return jax.jit(fn, donate_argnums=donate)
  return jax.jit(fn, in_shardings=ins, out_shardings=outs,
                 donate_argnums=donate)


def place_serving_args(spec: LMSpec, program: str, bucket: int, args):
  """device_put concrete call args onto the program's compiled
  shardings. AOT executables accept only exactly-placed arrays; the
  engine's host loop hands back eager-op results (cache installs,
  ladder gathers) whose placement GSPMD's propagation chose, so every
  dispatch re-pins them (a no-op for already-matching arrays)."""
  ins, _ = tp_shardings(spec, program, bucket)
  if ins is None:
    return args
  return tuple(jax.device_put(a, s) for a, s in zip(args, ins))


def kv_pool_pages(spec: LMSpec, bucket: int) -> int:
  """Pool size P for a paged cache at this bucket: scratch page 0 plus
  KV_POOL_FRACTION of the dense slab's page count, floored at one full
  sequence -- strictly below the dense ceiling for every bucket > 1,
  which is the auditor's serving-paged-kv bound."""
  pps = spec.pages_per_slot
  return max(pps + 1, 1 + math.ceil(bucket * pps * KV_POOL_FRACTION))


def _cache_shape(spec: LMSpec, bucket: int):
  if spec.kv_page_size:
    return (spec.n_layers, kv_pool_pages(spec, bucket),
            spec.kv_page_size, spec.n_heads, spec.head_dim)
  return (spec.n_layers, bucket, spec.max_len, spec.n_heads,
          spec.head_dim)


def init_cache(spec: LMSpec, bucket: int) -> CacheState:
  shape = _cache_shape(spec, bucket)
  return CacheState(
      k=jnp.zeros(shape, spec.dtype), v=jnp.zeros(shape, spec.dtype),
      pos=jnp.zeros((bucket,), jnp.int32),
      tok=jnp.zeros((bucket,), jnp.int32))


def grow_cache(cache: CacheState, spec: LMSpec,
               bucket: int) -> CacheState:
  """Migrate a cache onto a wider bucket (ladder growth): old slots
  keep their contents and positions, new slots start empty. Paged
  mode copies the pool prefix, so every already-allocated page index
  stays valid in the wider pool."""
  fresh = init_cache(spec, bucket)
  old = cache.k.shape[1]
  return CacheState(
      k=fresh.k.at[:, :old].set(cache.k),
      v=fresh.v.at[:, :old].set(cache.v),
      pos=fresh.pos.at[:cache.pos.shape[0]].set(cache.pos),
      tok=fresh.tok.at[:cache.tok.shape[0]].set(cache.tok))


def abstract_cache(spec: LMSpec, bucket: int) -> CacheState:
  """ShapeDtypeStruct cache (no allocation) -- AOT lowering input."""
  shape = _cache_shape(spec, bucket)
  return CacheState(
      k=jax.ShapeDtypeStruct(shape, spec.dtype),
      v=jax.ShapeDtypeStruct(shape, spec.dtype),
      pos=jax.ShapeDtypeStruct((bucket,), jnp.int32),
      tok=jax.ShapeDtypeStruct((bucket,), jnp.int32))


def decode_lowering_args(spec: LMSpec, bucket: int):
  """The ONE decode-step AOT lowering recipe: ``(fn, abstract_args,
  donate_argnums)``. Shared by the engine's executable cache
  (serving/engine._decode_exe) and the auditor's serving tracer
  (analysis/contracts.trace_serving_contract), so the serving_decode
  golden can never silently pin a program the engine no longer
  compiles."""
  cache = abstract_cache(spec, bucket)
  if spec.kv_page_size:
    args = (abstract_variables(spec), cache.k, cache.v, cache.pos,
            cache.tok,
            jax.ShapeDtypeStruct((bucket, spec.pages_per_slot),
                                 jnp.int32),
            jax.ShapeDtypeStruct((bucket,), jnp.bool_))
  else:
    args = (abstract_variables(spec), cache.k, cache.v, cache.pos,
            cache.tok, jax.ShapeDtypeStruct((bucket,), jnp.bool_))
  return decode_fn(spec), args, (1, 2)


def decode_fn(spec: LMSpec):
  """``(variables, k, v, pos, tok[, page_table], active) -> (next_tok,
  k', v', pos')`` -- one greedy decode step for every slot; inactive
  slots hold their token and position (their ring writes land on a
  slot the next prefill re-installs wholesale; in paged mode inactive
  slots' tables point at the scratch page, so their writes land
  nowhere live). The engine compiles this per bucket with the caches
  donated."""
  module = decode_module(spec)

  if spec.kv_page_size:
    def paged_step(variables, cache_k, cache_v, pos, tok, page_table,
                   active):
      variables = _serving_view(spec, variables)
      logits, (cache_k, cache_v) = module.apply(
          variables, tok, cache_k, cache_v, pos, page_table)
      nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
      nxt = jnp.where(active, nxt, tok)
      pos = pos + active.astype(jnp.int32)
      return nxt, cache_k, cache_v, pos

    return paged_step

  def step(variables, cache_k, cache_v, pos, tok, active):
    variables = _serving_view(spec, variables)
    logits, (cache_k, cache_v) = module.apply(variables, tok, cache_k,
                                              cache_v, pos)
    nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, tok)
    pos = pos + active.astype(jnp.int32)
    return nxt, cache_k, cache_v, pos

  return step


def prefill_fn(spec: LMSpec):
  """``(variables, packed, rows, last_pos, offsets) -> (first_tok,
  ek, ev)`` -- packed prefill, extract-only.

  ``packed`` is the (B_pack, 3, T) stack from packing.pack_prompts;
  per admitted request ``i``: ``rows[i]``/``offsets[i]`` locate its
  span inside the packed batch, ``last_pos[i] = offsets[i] +
  lengths[i] - 1`` its final prompt token. Returns each request's
  first sampled token plus its extracted per-layer K/V span,
  ring-length-padded -- (B_pack, L, T_cache, H, Dh). The engine
  scatters the spans into decode slots with plain jnp ops
  (``install_prefill``), which keeps this program keyed on the PACK
  bucket alone: a one-request wave pays a one-row prefill even while
  a wide decode bucket is in flight (the executable-set bound stays
  <= len(ladder) per program family).

  The fused head keeps the forward logits-free; only the (R, V) rows
  at the prompts' final positions are ever materialized. Cache spans
  are sliced STALE-INCLUSIVE: positions past a prompt's length hold a
  packed neighbor's K/V until decode overwrites them, which the
  ``slot <= pos`` attention mask makes exactly invisible
  (sequence.decode_attention)."""
  module = forward_module(spec, fused_head=True, return_kv=True)
  t_cache = spec.max_len

  def prefill(variables, packed, rows, last_pos, offsets):
    variables = _serving_view(spec, variables)
    head, _aux, (kst, vst) = module.apply(variables, packed)
    # First sampled token per request: the dense head's row, computed
    # only at the prompts' final positions (bit-identical to the
    # full dense-head forward's row -- tests/test_serving.py).
    hidden = head.hidden[rows, last_pos]              # (R, D)
    logits = hidden @ head.kernel.astype(spec.dtype)  # (R, V)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # Slice each request's K/V span out of its packed row. Padded
    # along T so a tail span slices clean.
    kp = jnp.pad(kst, ((0, 0), (0, 0), (0, t_cache), (0, 0), (0, 0)))
    vp = jnp.pad(vst, ((0, 0), (0, 0), (0, t_cache), (0, 0), (0, 0)))
    l_, h_, d_ = kst.shape[0], kst.shape[3], kst.shape[4]

    def span(arr, row, off):
      sl = lax.dynamic_slice(arr, (0, row, off, 0, 0),
                             (l_, 1, t_cache, h_, d_))
      return sl[:, 0]

    ek = jax.vmap(span, in_axes=(None, 0, 0))(kp, rows, offsets)
    ev = jax.vmap(span, in_axes=(None, 0, 0))(vp, rows, offsets)
    return first, ek, ev

  return prefill


def install_prefill(cache: CacheState, ek, ev, first, lengths,
                    slots) -> CacheState:
  """Scatter prefilled spans into their decode slots (plain jnp ops;
  out-of-range slot indices -- padding entries -- drop). ``ek``/``ev``
  are prefill_fn's (B_pack, L, T, H, Dh) extracts."""
  return CacheState(
      k=cache.k.at[:, slots].set(jnp.moveaxis(ek, 0, 1), mode="drop"),
      v=cache.v.at[:, slots].set(jnp.moveaxis(ev, 0, 1), mode="drop"),
      pos=cache.pos.at[slots].set(lengths, mode="drop"),
      tok=cache.tok.at[slots].set(first, mode="drop"))


def install_prefill_paged(cache: CacheState, ek, ev, first, lengths,
                          slots, req_tables) -> CacheState:
  """Paged-mode prefill install: chop each request's (L, T, H, Dh)
  span into pages_per_slot (L, page, H, Dh) pages and scatter them
  into the pool rows ``req_tables`` names. ``req_tables`` is
  (B_pack, pages_per_slot) int32 holding allocated pool-row ids in
  LOGICAL page order, with an out-of-range sentinel (>= P) on
  unallocated pages and padding rows -- ``mode="drop"`` discards
  those, so only allocated pages are written (the dense install's
  stale-inclusive discipline, page-granular)."""
  l_, page = cache.k.shape[0], cache.k.shape[2]
  bpk, _, t, h_, dh = ek.shape
  pps = t // page
  ids = jnp.asarray(req_tables, jnp.int32).reshape(-1)  # (B_pack*pps,)

  def paginate(arr):
    # (B_pack, L, T, H, Dh) -> (L, B_pack*pps, page, H, Dh), b-major
    # page-minor to match ids' row-major flattening.
    pag = arr.reshape(bpk, l_, pps, page, h_, dh)
    return jnp.moveaxis(pag, 1, 0).reshape(l_, bpk * pps, page, h_, dh)

  return CacheState(
      k=cache.k.at[:, ids].set(paginate(ek), mode="drop"),
      v=cache.v.at[:, ids].set(paginate(ev), mode="drop"),
      pos=cache.pos.at[slots].set(lengths, mode="drop"),
      tok=cache.tok.at[slots].set(first, mode="drop"))


def verify_chunk(spec: LMSpec) -> int:
  """Sequence-chunk width for the verify program's argmax head
  (gcd-clamped so it divides max_len exactly)."""
  return math.gcd(spec.max_len,
                  max(1, spec.max_len // VERIFY_CHUNK_DENOM))


def verify_fn(spec: LMSpec):
  """``(variables, tokens) -> preds`` -- the speculative TARGET
  verifier: ONE prefill-shaped full forward over (B, max_len) token
  rows, returning the greedy argmax at EVERY position --
  ``preds[b, t]`` is the target's greedy choice for position t+1 given
  ``tokens[b, :t+1]``. The engine lays each slot's confirmed history
  ++ draft proposals into a row, runs this once, and accepts the
  longest agreeing prefix -- so k proposals cost one target dispatch
  instead of k, and greedy output is token-identical to plain greedy
  by construction (causality: preds at position t never sees tokens
  past t, so an accepted prefix's predictions match what sequential
  greedy decode would have produced).

  The fused head keeps this logits-free in the large: hidden states
  are chunked along T (verify_chunk positions at a time) through a
  ``lax.scan``, so the biggest live logits buffer is
  (B, chunk, V) << the (B, T, V) dense-head tensor -- the
  serving-verify-bounded audit rule pins that."""
  module = forward_module(spec, fused_head=True)
  chunk = verify_chunk(spec)

  def verify(variables, tokens):
    variables = _serving_view(spec, variables)
    head, _aux = module.apply(variables, tokens)
    kernel = head.kernel.astype(spec.dtype)
    b, t, dm = head.hidden.shape
    hc = head.hidden.reshape(b, t // chunk, chunk, dm)
    hc = jnp.swapaxes(hc, 0, 1)                  # (n_chunks, B, c, D)

    def step(carry, h):
      logits = h.astype(spec.dtype) @ kernel     # (B, chunk, V)
      return carry, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    _, preds = lax.scan(step, None, hc)          # (n_chunks, B, c)
    return jnp.swapaxes(preds, 0, 1).reshape(b, t)

  return verify


def verify_lowering_args(spec: LMSpec, bucket: int):
  """AOT lowering recipe for the verify program (program family
  ``serving_verify``): no donation -- its only inputs are the frozen
  weights and the (B, max_len) token rows."""
  args = (abstract_variables(spec),
          jax.ShapeDtypeStruct((bucket, spec.max_len), jnp.int32))
  return verify_fn(spec), args, ()


def reference_generate(spec: LMSpec, variables, prompt,
                       max_new_tokens: int) -> Tuple[Any, Any]:
  """Greedy generation straight through the full-sequence forward --
  the engine-free oracle the e2e tests compare engine output against.
  O(T^2) per token; test instrument only. Returns (first_token,
  [all generated tokens])."""
  module = forward_module(spec, fused_head=False)
  apply = jax.jit(module.apply)
  out = []
  toks = list(int(t) for t in jnp.asarray(prompt))
  for _ in range(max_new_tokens):
    # Fixed (1, max_len) shape (zero-padded tail): causal attention
    # makes the pad rows invisible to position len-1, and the fixed
    # shape keeps the tiled path's block divisibility and ONE compile.
    batch = jnp.zeros((1, spec.max_len), jnp.int32)
    batch = batch.at[0, :len(toks)].set(jnp.asarray(toks, jnp.int32))
    logits, _ = apply(variables, batch)
    nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
    out.append(nxt)
    toks.append(nxt)
  return (out[0] if out else None), out


# The INT8 accuracy gate (ISSUE 16): minimum prefix-conditioned greedy
# agreement for quantized serving to be admitted. The metric is
# NEXT-TOKEN agreement given the f32 arm's confirmed prefix (the
# speculative-decoding acceptance metric), not whole-sequence zip --
# zip charges every post-flip token to the first flip (greedy decode
# compounds), which says nothing about per-step accuracy.
QUANTIZE_AGREEMENT_BAR = 0.99


def quantize_agreement(spec: LMSpec, variables, prompts,
                       max_new_tokens: int) -> Dict[str, Any]:
  """Measure the INT8 accuracy delta on a seeded probe: generate the
  f32 arm's greedy rows (batched, teacher-forced through verify_fn's
  full forward), then score the QUANTIZED model's greedy choice at
  every generated position against them, plus the max logit delta.

  ``spec`` must set ``quantize``; ``variables`` is the float tree.
  Returns {agreement, total, max_logit_delta, logit_scale, passed} --
  the caller gates quantized serving on ``passed`` (the bar is
  QUANTIZE_AGREEMENT_BAR). Random-init weights are the adversarial
  case (argmax margins are razor-thin, so per-mille logit noise flips
  tokens); a gate that admits them would admit anything."""
  if not spec.quantize:
    raise ValueError("quantize_agreement needs a quantized spec")
  fspec = dataclasses.replace(spec, quantize=None)
  fvf = jax.jit(verify_fn(fspec))
  qvf = jax.jit(verify_fn(spec))
  qvars = prepare_variables(spec, variables)
  n = len(prompts)
  rows = np.zeros((n, spec.max_len), np.int32)
  lens = []
  for i, prompt in enumerate(prompts):
    p = np.asarray(prompt, np.int32).reshape(-1)
    p = p[:max(1, spec.max_len - max_new_tokens)]
    rows[i, :p.size] = p
    lens.append(p.size)
  q0 = list(lens)
  for _ in range(max_new_tokens):
    preds = np.asarray(fvf(variables, jnp.asarray(rows)))
    for i in range(n):
      if lens[i] < spec.max_len:
        rows[i, lens[i]] = preds[i, lens[i] - 1]
        lens[i] += 1
  qpreds = np.asarray(qvf(qvars, jnp.asarray(rows)))
  total = agree = 0
  for i in range(n):
    for t in range(q0[i], lens[i]):
      total += 1
      agree += int(qpreds[i, t - 1] == rows[i, t])
  agreement = agree / max(total, 1)
  # Max logit delta over a bounded slice of the probe rows (the
  # whole-probe forward would be a (N, T, V) pair of tensors).
  module = forward_module(fspec, fused_head=False)
  apply = jax.jit(module.apply)
  probe = jnp.asarray(rows[:min(n, 4)])
  ref, _ = apply(variables, probe)
  got, _ = apply(quantization.dequantize_variables(qvars,
                                                   spec.param_dtype),
                 probe)
  delta = float(jnp.max(jnp.abs(got - ref)))
  scale = float(jnp.max(jnp.abs(ref)))
  return {"agreement": agreement, "total": total,
          "max_logit_delta": delta, "logit_scale": scale,
          "passed": agreement >= QUANTIZE_AGREEMENT_BAR}
