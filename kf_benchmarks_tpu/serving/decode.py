"""KV-cache LM decode programs for the serving engine.

Device-side half of the serving path (ref: the reference's closest
analog is the frozen forward-only loop, benchmark_cnn.py:2405-2525;
everything autoregressive here is beyond-reference). Three programs,
each compiled ahead of time per bucket by the engine:

* **prefill** -- mixed-length prompts, first-fit packed into one
  ``(B_pack, 3, T)`` stack (data/packing.py ``pack_prompts``), run
  through the full-sequence forward with ``return_kv=True``: one
  dispatch produces every prompt's first sampled token (from the fused
  head's hidden states -- no (B, T, V) logits tensor ever exists) AND
  its per-layer K/V span, which is sliced out of the packed rows and
  installed into the ring-buffer cache slots in the same program.
* **decode step** -- one token per active slot through the
  ``decode=True`` transformer_lm path: write K/V into the ring at
  ``pos``, attend over ``slot <= pos``, greedy-sample the next token
  in-program. Caches are donated, so the step updates them in place --
  the executable's only traffic is the (B,) token/pos vectors.
* **cache state** -- the explicit ``(L, B, T, H, Dh)`` K/V ring
  buffers plus per-slot ``pos``/``tok`` vectors; per-slot positions are
  what lets continuous batching refill one freed slot while its
  neighbors keep decoding.

Numerical contract (tests/test_serving.py): with ``decode_exact=True``
the per-token f32 logits of the incremental path are BIT-IDENTICAL to
the full-sequence forward at every prefix length, for both the
blockwise CPU schedule and the flash path's CPU reference (the masked
positions contribute exactly zero; the exact mode reuses the full
forward's op graph -- see sequence.decode_attention). The fast 1-row
schedule agrees to float rounding (~2e-6 measured) and is the
production default.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from kf_benchmarks_tpu.models import transformer_lm as lm


@dataclasses.dataclass(frozen=True)
class LMSpec:
  """The served LM's shape -- defaults are the zoo transformer_lm, so
  a serving benchmark exercises the same program family the training
  harness measures. ``max_len`` is both the ring-buffer length and the
  packed-prefill width; prompts + generation beyond it fall into the
  ring's sliding window."""
  vocab: int = lm.VOCAB
  d_model: int = lm.D_MODEL
  n_layers: int = lm.N_LAYERS
  n_heads: int = lm.N_HEADS
  d_ff: int = lm.D_FF
  max_len: int = lm.SEQ_LEN
  attn_block: int = lm.ATTN_BLOCK
  attn_impl: str = "tiled"
  scan_layers: bool = True
  decode_exact: bool = False
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32

  @property
  def head_dim(self) -> int:
    return self.d_model // self.n_heads

  def config(self) -> dict:
    """The fingerprint payload (analysis/baseline.config_fingerprint_key
    keys the executable cache and compile ledger on it)."""
    return {
        "vocab": self.vocab, "d_model": self.d_model,
        "n_layers": self.n_layers, "n_heads": self.n_heads,
        "d_ff": self.d_ff, "max_len": self.max_len,
        "attn_block": self.attn_block, "attn_impl": self.attn_impl,
        "scan_layers": self.scan_layers,
        "decode_exact": self.decode_exact,
        "dtype": jnp.dtype(self.dtype).name,
        "param_dtype": jnp.dtype(self.param_dtype).name,
    }


class CacheState(NamedTuple):
  """The explicit ring-buffer decode state. ``k``/``v``:
  (L, B, T, H, Dh); ``pos``: (B,) absolute position of each slot's
  CURRENT token; ``tok``: (B,) the token at that position (not yet in
  the cache -- the next decode step writes it)."""
  k: Any
  v: Any
  pos: Any
  tok: Any


def _module_kwargs(spec: LMSpec) -> dict:
  return dict(vocab=spec.vocab, d_model=spec.d_model,
              n_layers=spec.n_layers, n_heads=spec.n_heads,
              d_ff=spec.d_ff, attn_block=spec.attn_block,
              attn_q_block=spec.attn_block, attn_impl=spec.attn_impl,
              scan_layers=spec.scan_layers, max_len=spec.max_len,
              dtype=spec.dtype, param_dtype=spec.param_dtype)


def forward_module(spec: LMSpec, fused_head: bool = True,
                   return_kv: bool = False):
  """The full-sequence forward (prefill / oracle reference)."""
  return lm._TransformerLMModule(fused_head=fused_head,
                                 return_kv=return_kv,
                                 **_module_kwargs(spec))


def decode_module(spec: LMSpec):
  """The single-token KV-ring decode module."""
  return lm._TransformerLMModule(fused_head=False, decode=True,
                                 decode_exact=spec.decode_exact,
                                 **_module_kwargs(spec))


def init_variables(spec: LMSpec, seed: int = 0):
  """Synthetic serving weights (the engine serves frozen weights; any
  checkpointed transformer_lm param tree of the same shape drops in)."""
  module = forward_module(spec, fused_head=True)
  rng = jax.random.PRNGKey(seed)
  sample = jnp.zeros((1, spec.max_len), jnp.int32)
  return module.init({"params": rng, "dropout": rng}, sample)


def abstract_variables(spec: LMSpec):
  """ShapeDtypeStruct variable tree (nothing executes) -- the AOT
  lowering input and the auditor's tracing input."""
  module = forward_module(spec, fused_head=True)
  sample = jnp.zeros((1, spec.max_len), jnp.int32)
  return jax.eval_shape(
      lambda: module.init({"params": jax.random.PRNGKey(0),
                           "dropout": jax.random.PRNGKey(0)}, sample))


def init_cache(spec: LMSpec, bucket: int) -> CacheState:
  shape = (spec.n_layers, bucket, spec.max_len, spec.n_heads,
           spec.head_dim)
  return CacheState(
      k=jnp.zeros(shape, spec.dtype), v=jnp.zeros(shape, spec.dtype),
      pos=jnp.zeros((bucket,), jnp.int32),
      tok=jnp.zeros((bucket,), jnp.int32))


def grow_cache(cache: CacheState, spec: LMSpec,
               bucket: int) -> CacheState:
  """Migrate a cache onto a wider bucket (ladder growth): old slots
  keep their contents and positions, new slots start empty."""
  fresh = init_cache(spec, bucket)
  old = cache.k.shape[1]
  return CacheState(
      k=fresh.k.at[:, :old].set(cache.k),
      v=fresh.v.at[:, :old].set(cache.v),
      pos=fresh.pos.at[:old].set(cache.pos),
      tok=fresh.tok.at[:old].set(cache.tok))


def abstract_cache(spec: LMSpec, bucket: int) -> CacheState:
  """ShapeDtypeStruct cache (no allocation) -- AOT lowering input."""
  shape = (spec.n_layers, bucket, spec.max_len, spec.n_heads,
           spec.head_dim)
  return CacheState(
      k=jax.ShapeDtypeStruct(shape, spec.dtype),
      v=jax.ShapeDtypeStruct(shape, spec.dtype),
      pos=jax.ShapeDtypeStruct((bucket,), jnp.int32),
      tok=jax.ShapeDtypeStruct((bucket,), jnp.int32))


def decode_lowering_args(spec: LMSpec, bucket: int):
  """The ONE decode-step AOT lowering recipe: ``(fn, abstract_args,
  donate_argnums)``. Shared by the engine's executable cache
  (serving/engine._decode_exe) and the auditor's serving tracer
  (analysis/contracts.trace_serving_contract), so the serving_decode
  golden can never silently pin a program the engine no longer
  compiles."""
  cache = abstract_cache(spec, bucket)
  args = (abstract_variables(spec), cache.k, cache.v, cache.pos,
          cache.tok, jax.ShapeDtypeStruct((bucket,), jnp.bool_))
  return decode_fn(spec), args, (1, 2)


def decode_fn(spec: LMSpec):
  """``(variables, k, v, pos, tok, active) -> (next_tok, k', v',
  pos')`` -- one greedy decode step for every slot; inactive slots
  hold their token and position (their ring writes land on a slot the
  next prefill re-installs wholesale). The engine compiles this per
  bucket with the caches donated."""
  module = decode_module(spec)

  def step(variables, cache_k, cache_v, pos, tok, active):
    logits, (cache_k, cache_v) = module.apply(variables, tok, cache_k,
                                              cache_v, pos)
    nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
    nxt = jnp.where(active, nxt, tok)
    pos = pos + active.astype(jnp.int32)
    return nxt, cache_k, cache_v, pos

  return step


def prefill_fn(spec: LMSpec):
  """``(variables, packed, rows, last_pos, offsets) -> (first_tok,
  ek, ev)`` -- packed prefill, extract-only.

  ``packed`` is the (B_pack, 3, T) stack from packing.pack_prompts;
  per admitted request ``i``: ``rows[i]``/``offsets[i]`` locate its
  span inside the packed batch, ``last_pos[i] = offsets[i] +
  lengths[i] - 1`` its final prompt token. Returns each request's
  first sampled token plus its extracted per-layer K/V span,
  ring-length-padded -- (B_pack, L, T_cache, H, Dh). The engine
  scatters the spans into decode slots with plain jnp ops
  (``install_prefill``), which keeps this program keyed on the PACK
  bucket alone: a one-request wave pays a one-row prefill even while
  a wide decode bucket is in flight (the executable-set bound stays
  <= len(ladder) per program family).

  The fused head keeps the forward logits-free; only the (R, V) rows
  at the prompts' final positions are ever materialized. Cache spans
  are sliced STALE-INCLUSIVE: positions past a prompt's length hold a
  packed neighbor's K/V until decode overwrites them, which the
  ``slot <= pos`` attention mask makes exactly invisible
  (sequence.decode_attention)."""
  module = forward_module(spec, fused_head=True, return_kv=True)
  t_cache = spec.max_len

  def prefill(variables, packed, rows, last_pos, offsets):
    head, _aux, (kst, vst) = module.apply(variables, packed)
    # First sampled token per request: the dense head's row, computed
    # only at the prompts' final positions (bit-identical to the
    # full dense-head forward's row -- tests/test_serving.py).
    hidden = head.hidden[rows, last_pos]              # (R, D)
    logits = hidden @ head.kernel.astype(spec.dtype)  # (R, V)
    first = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # Slice each request's K/V span out of its packed row. Padded
    # along T so a tail span slices clean.
    kp = jnp.pad(kst, ((0, 0), (0, 0), (0, t_cache), (0, 0), (0, 0)))
    vp = jnp.pad(vst, ((0, 0), (0, 0), (0, t_cache), (0, 0), (0, 0)))
    l_, h_, d_ = kst.shape[0], kst.shape[3], kst.shape[4]

    def span(arr, row, off):
      sl = lax.dynamic_slice(arr, (0, row, off, 0, 0),
                             (l_, 1, t_cache, h_, d_))
      return sl[:, 0]

    ek = jax.vmap(span, in_axes=(None, 0, 0))(kp, rows, offsets)
    ev = jax.vmap(span, in_axes=(None, 0, 0))(vp, rows, offsets)
    return first, ek, ev

  return prefill


def install_prefill(cache: CacheState, ek, ev, first, lengths,
                    slots) -> CacheState:
  """Scatter prefilled spans into their decode slots (plain jnp ops;
  out-of-range slot indices -- padding entries -- drop). ``ek``/``ev``
  are prefill_fn's (B_pack, L, T, H, Dh) extracts."""
  return CacheState(
      k=cache.k.at[:, slots].set(jnp.moveaxis(ek, 0, 1), mode="drop"),
      v=cache.v.at[:, slots].set(jnp.moveaxis(ev, 0, 1), mode="drop"),
      pos=cache.pos.at[slots].set(lengths, mode="drop"),
      tok=cache.tok.at[slots].set(first, mode="drop"))


def reference_generate(spec: LMSpec, variables, prompt,
                       max_new_tokens: int) -> Tuple[Any, Any]:
  """Greedy generation straight through the full-sequence forward --
  the engine-free oracle the e2e tests compare engine output against.
  O(T^2) per token; test instrument only. Returns (first_token,
  [all generated tokens])."""
  module = forward_module(spec, fused_head=False)
  apply = jax.jit(module.apply)
  out = []
  toks = list(int(t) for t in jnp.asarray(prompt))
  for _ in range(max_new_tokens):
    # Fixed (1, max_len) shape (zero-padded tail): causal attention
    # makes the pad rows invisible to position len-1, and the fixed
    # shape keeps the tiled path's block divisibility and ONE compile.
    batch = jnp.zeros((1, spec.max_len), jnp.int32)
    batch = batch.at[0, :len(toks)].set(jnp.asarray(toks, jnp.int32))
    logits, _ = apply(variables, batch)
    nxt = int(jnp.argmax(logits[0, len(toks) - 1]))
    out.append(nxt)
    toks.append(nxt)
  return (out[0] if out else None), out
