"""Production serving path: continuous-batching AOT inference.

The reference's inference story stops at ``--forward_only`` -- one
static synthetic batch timed in a loop, no request path (ref:
scripts/tf_cnn_benchmarks/benchmark_cnn.py:2405-2525 _preprocess_graph
freeze/serve, flags :615-620 --trt_mode). This subpackage is the
request-driven system on top of the pieces the repo already measures:

* ``decode.py`` -- the KV-ring-buffer LM decode programs: packed
  prefill (mixed-length prompts in ONE dispatch, riding
  data/packing.py), the single-token decode step
  (models/transformer_lm.py ``decode=True``; attention =
  parallel/sequence.decode_attention -- the Pallas flash kernel's
  decode mode on TPU, the blockwise/full schedule on CPU), greedy
  sampling in-program, caches donated in place.
* ``engine.py`` -- the host-side request engine: bounded bucket-ladder
  executable cache (AOT ``jit(...).lower(...).compile()``, keyed on
  ``analysis/baseline.config_fingerprint_key``), continuous in-flight
  batching (freed slots refill every decode step) vs static
  batch-and-drain, SLO-aware admission control (queue-depth rejection,
  TTFT-deadline expiry, per-tenant token budgets), request spans on the
  ``RunTrace`` timeline and ``serving/*`` metrics in the registry
  schema.
"""

from kf_benchmarks_tpu.serving.decode import (  # noqa: F401
    CacheState, LMSpec, decode_fn, decode_module, forward_module,
    init_cache, init_variables, prefill_fn)
from kf_benchmarks_tpu.serving.engine import (  # noqa: F401
    EngineConfig, Request, RequestResult, ServingEngine, bucket_for,
    poisson_workload)
