"""Post-training weight quantization for the frozen serving path.

The reference's serving mode converts the frozen graph with TensorRT at
FP32/FP16/INT8 precision (ref: scripts/tf_cnn_benchmarks/
benchmark_cnn.py:2466-2486 _GraphInfo TRT conversion, flags :615-620
--trt_mode). The TPU-native INT8 analog is weight-only post-training
quantization of the AOT-exported forward program:

* each large float kernel is stored as symmetric per-output-channel
  int8 (q = round(w / scale), scale = max|w| / 127 over the output
  channel), biases/norm parameters stay float;
* dequantization (q * scale -> compute dtype) happens INSIDE the
  exported program, so the serialized artifact carries 1-byte weight
  constants (~4x smaller than f32) and the chip reads weights from HBM
  at a quarter of the bandwidth -- the win TRT INT8 buys on GPUs, in
  the place a TPU serving program actually spends it;
* matmuls/convs execute in the compute dtype (bf16 on TPU) after the
  inline dequant; XLA fuses the scale multiply into the weight load.

Activation quantization (TRT's calibration pass) is deliberately NOT
replicated: on TPU the MXU computes bf16 at full rate, so activation
int8 buys bandwidth only on the (small) activation tensors while
costing a calibration sweep; weight-only PTQ keeps the artifact
self-contained, needs no calibration data, and preserves accuracy
(pinned by tests/test_quantization.py's accuracy-delta check).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Leaves smaller than this stay float: biases, norm scales, and other
# vectors are bandwidth-irrelevant and precision-critical.
MIN_QUANT_ELEMS = 4096

_QKEY = "__int8__"
_SKEY = "__scale__"


def _is_qleaf(x) -> bool:
  return isinstance(x, dict) and _QKEY in x and _SKEY in x


# A 4-D kernel whose trailing axis is at most this wide (and narrower
# than its in-features axis) is treated as a TF-layout depthwise kernel
# (h, w, in, multiplier): real depthwise multipliers are tiny (1-8),
# while genuine output-feature axes are channel-scale wide.
DEPTHWISE_MULTIPLIER_MAX = 8


def _scale_axes(w) -> tuple:
  """Axes the per-channel absmax reduces over: everything except the
  output channels.

  Standard kernels put output features LAST -- dense (in, out), conv
  (h, w, in, out), and the flax depthwise layout (h, w, 1, in*mult) --
  so the reduction covers all leading axes. A TF-layout depthwise
  kernel (h, w, in, multiplier) spreads its output channels over the
  last TWO axes: reducing over (h, w, in) there would collapse every
  input channel into one multiplier-wide scale (multiplier=1: a single
  scale for the whole kernel), losing the per-channel dynamic range the
  scheme exists for. Those reduce over the spatial axes only, giving
  one scale per (in, multiplier) output channel.
  """
  if (w.ndim == 4 and w.shape[3] <= DEPTHWISE_MULTIPLIER_MAX
      and w.shape[3] < w.shape[2]):
    return (0, 1)
  return tuple(range(w.ndim - 1))


def quantize_variables(variables, min_elems: int = MIN_QUANT_ELEMS):
  """Float kernels -> {int8 q, f32 per-out-channel scale} leaves.

  Symmetric per-output-channel quantization: scale = max|w| / 127 over
  each output channel, with the channel axes resolved per layout
  (``_scale_axes``; the depthwise (h, w, in, multiplier) layout keeps
  per-(in, multiplier) scales). Leaves that are not float, have fewer
  than 2 axes, or fewer than ``min_elems`` elements pass through
  unchanged.
  """

  def quant(w):
    if (not isinstance(w, jnp.ndarray) and not hasattr(w, "dtype")):
      return w
    if (w.ndim < 2 or w.size < min_elems
        or not jnp.issubdtype(w.dtype, jnp.floating)):
      return w
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=_scale_axes(w))
    scale = jnp.maximum(absmax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return {_QKEY: q.astype(jnp.int8), _SKEY: scale}

  return jax.tree.map(quant, variables)


def dequantize_variables(qvars, dtype=jnp.float32):
  """Inverse of quantize_variables, usable inside jit: int8 leaves are
  rebuilt as (q * scale) in ``dtype``; float leaves pass through."""

  def dequant(leaf):
    if _is_qleaf(leaf):
      return (leaf[_QKEY].astype(jnp.float32)
              * leaf[_SKEY]).astype(dtype)
    return leaf

  return jax.tree.map(dequant, qvars, is_leaf=_is_qleaf)


def has_quantized_leaves(tree) -> bool:
  """True if any leaf is a {q, scale} quantized dict -- the
  idempotence check for serving's prepare_variables (a tree quantized
  once must not be re-quantized: int8 leaves under quantize would be
  treated as tiny float kernels and corrupt the scales)."""
  return any(_is_qleaf(leaf)
             for leaf in jax.tree.leaves(tree, is_leaf=_is_qleaf))


def quantized_fraction(qvars) -> float:
  """Fraction of parameter ELEMENTS stored as int8 -- a sanity metric
  for logs/tests (a model whose kernels all fell under the size
  threshold serves no quantization purpose)."""
  q_elems = total = 0
  for leaf in jax.tree.leaves(qvars, is_leaf=_is_qleaf):
    if _is_qleaf(leaf):
      q_elems += leaf[_QKEY].size
      total += leaf[_QKEY].size
    elif hasattr(leaf, "size"):
      total += leaf.size
  return q_elems / max(total, 1)
