"""Optimizer construction.

(ref: benchmark_cnn.py:1172-1205 get_optimizer). The KungFu wrapper
injection of the reference happens in the parallel layer here
(strategies.KungFuStrategy hooks), keeping optimizers pure optax
transformations. LARS is added beyond the reference set -- it is the
standard large-batch ResNet optimizer on TPU pods.
"""

from __future__ import annotations

from typing import Callable, Union

import optax


def get_optimizer(params, learning_rate: Union[float, Callable]):
  """Build the optax optimizer from params (ref: benchmark_cnn.py:1172-1205)."""
  opt = params.optimizer
  if opt == "sgd":
    tx = optax.sgd(learning_rate)
  elif opt == "momentum":
    tx = optax.sgd(learning_rate, momentum=params.momentum, nesterov=True)
  elif opt == "rmsprop":
    tx = optax.rmsprop(learning_rate, decay=params.rmsprop_decay,
                       momentum=params.rmsprop_momentum,
                       eps=params.rmsprop_epsilon)
  elif opt == "adam":
    tx = optax.adam(learning_rate, b1=params.adam_beta1,
                    b2=params.adam_beta2, eps=params.adam_epsilon)
  elif opt == "lars":
    tx = optax.lars(learning_rate, momentum=params.momentum)
  else:
    raise ValueError(f"Optimizer {opt!r} not supported")
  if params.gradient_clip is not None:
    tx = optax.chain(
        optax.clip(params.gradient_clip), tx)
  return tx
