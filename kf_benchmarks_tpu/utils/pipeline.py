"""Lag-N pipelined metrics fetching with real per-step wall times.

Round-1 measured the timed loop two ways and both were wrong in one
direction or the other: blocking on the current step's metrics every
iteration costs a full host<->device round-trip per step (ruinous when the
chip sits behind a network tunnel: measured 389 img/s vs 2560 with this
pipeline on ResNet-50/v5e), while fetching one flat window average made the
printed uncertainty/jitter constants (always 0.0). This module gives both
honest per-step statistics and full dispatch pipelining:

* Each dispatched step's metrics enter a lag-``N`` ring; an async
  device-to-host copy is started immediately so the transfer runs as soon
  as the step completes on device.
* ``N`` iterations later the value is read (by then the copy has landed, so
  the read does not stall the dispatch queue), and the wall-clock interval
  between consecutive reads is recorded. At steady state the loop is
  rate-limited by step completion, so these arrival intervals ARE the real
  per-step device times -- the pipelined analog of the reference's
  per-sess.run timing (ref: benchmark_cnn.py:786-884 benchmark_one_step,
  :887-902 get_perf_timing).
* Host-side pauses that are not step work (checkpoint saves, mid-train
  eval) are excluded from the next interval via ``note_aux_time`` -- the
  analog of the reference keeping checkpoint time out of its step timer.

Chunked dispatches (--steps_per_dispatch=K): one ``push`` carries K
steps' stacked metrics (``count=K``). The ring and the lag count
DISPATCHES, the resolution unstacks the K per-step metric trees host-side
so every printed value is still the exact value for its step. Timing is
HONEST at chunk granularity only: the host observes one arrival per
chunk, so each of the K steps is attributed interval/K and the printed
uncertainty/jitter measure chunk-to-chunk variation, not within-chunk
variation (within a chunk there is no host-visible boundary to time --
and ``block_until_ready`` cannot be trusted to make one on the tunneled
backend, see utils/sync.py).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np


class CompletedStep:
  """A resolved step: its 1-based index, host metrics, and wall interval.

  ``chunk_len``/``chunk_interval`` carry the dispatch this step arrived
  in: (1, interval) for single-step dispatches; for a K-step chunk every
  member reports the chunk's size and full wall interval (its own
  ``interval`` is the amortized 1/K share). ``chunk_end`` is True on the
  dispatch's final step, so per-dispatch consumers (chunk timing rows)
  count each dispatch once.
  """

  __slots__ = ("index", "metrics", "interval", "chunk_len",
               "chunk_interval", "chunk_end")

  def __init__(self, index: int, metrics: Dict[str, Any], interval: float,
               chunk_len: int = 1, chunk_interval: Optional[float] = None,
               chunk_end: bool = True):
    self.index = index
    self.metrics = metrics
    self.interval = interval
    self.chunk_len = chunk_len
    self.chunk_interval = (interval if chunk_interval is None
                           else chunk_interval)
    self.chunk_end = chunk_end


def _start_async_copy(metrics) -> None:
  for leaf in jax.tree.leaves(metrics):
    copy = getattr(leaf, "copy_to_host_async", None)
    if copy is not None:
      copy()


class MetricsPipeline:
  """Keeps ``lag`` dispatches in flight; resolves older ones without
  stalling.

  Usage:
    pipe = MetricsPipeline(lag=2)
    for i in range(num_batches):
      state, metrics = step(...)
      for done in pipe.push(i + 1, metrics):
        handle(done)            # done.interval is a real per-step time
    for done in pipe.flush():
      handle(done)

  A chunked dispatch covering steps ``index-count+1 .. index`` pushes its
  stacked metrics once with ``count=K``; resolution yields K
  CompletedSteps in step order.
  """

  def __init__(self, lag: int = 2):
    self.lag = max(0, lag)
    self._ring: "collections.deque[Tuple[int, Any, int]]" = \
        collections.deque()
    self._last_time: Optional[float] = None
    self._aux_time = 0.0

  def reset_clock(self) -> None:
    """Restart interval timing (after a drain, reshape, or loop start)."""
    self._last_time = time.time()
    self._aux_time = 0.0

  def note_aux_time(self, seconds: float) -> None:
    """Exclude ``seconds`` of non-step host work from the next interval."""
    self._aux_time += max(0.0, seconds)

  def _resolve(self, index: int, metrics, count: int) -> \
      List[CompletedStep]:
    host = jax.device_get(metrics)
    now = time.time()
    if self._last_time is None:
      self._last_time = now
      interval = 0.0
    else:
      interval = max(1e-9, now - self._last_time - self._aux_time)
    self._last_time = now
    self._aux_time = 0.0
    if count <= 1:
      return [CompletedStep(index, host, interval)]
    # Unstack the chunk host-side: leaf j of step j is row j of each
    # stacked (K,)-leading leaf; unstacked leaves (a metric that is not
    # per-step) pass through unchanged. Each step gets the amortized
    # interval share (see module docstring on chunk-window timing).
    per = interval / count

    def pick(j):
      def slice_leaf(x):
        arr = np.asarray(x)
        if arr.ndim and arr.shape[0] == count:
          return arr[j]
        return x
      return jax.tree.map(slice_leaf, host)

    return [CompletedStep(index - count + 1 + j, pick(j), per,
                          chunk_len=count, chunk_interval=interval,
                          chunk_end=(j == count - 1))
            for j in range(count)]

  def push(self, index: int, metrics,
           count: int = 1) -> List[CompletedStep]:
    """Add a just-dispatched step (or K-step chunk ending at ``index``);
    return any steps whose dispatch left the ring."""
    _start_async_copy(metrics)
    self._ring.append((index, metrics, count))
    done = []
    while len(self._ring) > self.lag:
      done.extend(self._resolve(*self._ring.popleft()))
    return done

  def flush(self) -> List[CompletedStep]:
    """Resolve everything in flight (end of loop or forced sync point)."""
    done = []
    while self._ring:
      done.extend(self._resolve(*self._ring.popleft()))
    return done

  def __len__(self) -> int:
    return len(self._ring)
