"""Device-queue synchronization that works on the tunneled TPU backend.

``jax.block_until_ready`` returns before device execution completes on
the tunneled (axon) TPU backend (measured in PERF.md's round-2
follow-up: a "blocked" timing loop reported physically impossible
throughput), so every wall-clock boundary -- warmup end, init end,
trace spans, microbenchmark regions -- must synchronize through a real
value fetch instead.
"""

import jax


def drain(tree) -> None:
  """Block until all device work feeding ``tree`` has completed.

  Fetches every addressable shard of the smallest array leaf, keeping
  the host transfer negligible. Per-device execution is in-order, so
  once each device's shard of the leaf is fetched, everything enqueued
  on that device before the leaf's producer has completed. Fetching all
  shards (not the assembled array) matters for replicated leaves, where
  assembling would read one device and leave the others' queues live.
  """
  leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
  if not leaves:
    return
  leaf = min(leaves, key=lambda x: x.size)
  shards = getattr(leaf, "addressable_shards", None)
  if shards:
    jax.device_get([s.data for s in shards])
  else:
    jax.device_get(leaf)
