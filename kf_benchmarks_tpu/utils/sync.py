"""Device-queue synchronization that works on the tunneled TPU backend.

``jax.block_until_ready`` returns before device execution completes on
the tunneled (axon) TPU backend (measured in PERF.md's round-2
follow-up: a "blocked" timing loop reported physically impossible
throughput), so every wall-clock boundary -- warmup end, init end,
trace spans, microbenchmark regions -- must synchronize through a real
value fetch instead.
"""

import jax


def drain(tree) -> None:
  """Block until all device work feeding ``tree`` has completed.

  Fetches every addressable shard of the smallest array leaf *per
  distinct device set*, keeping the host transfer negligible. Per-device
  execution is in-order, so once each device's shard of a leaf is
  fetched, everything enqueued on that device before the leaf's producer
  has completed. Fetching all shards (not the assembled array) matters
  for replicated leaves, where assembling would read one device and
  leave the others' queues live. Grouping by device set matters when a
  tree mixes differently-committed leaves (e.g. a single-device scalar
  alongside mesh-sharded arrays): draining only the globally smallest
  leaf would leave the other devices' queues live.
  """
  leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
  if not leaves:
    return
  by_devices = {}
  for leaf in leaves:
    shards = getattr(leaf, "addressable_shards", None)
    devices = (frozenset(s.device.id for s in shards) if shards
               else frozenset())
    best = by_devices.get(devices)
    if best is None or leaf.size < best.size:
      by_devices[devices] = leaf
  for leaf in by_devices.values():
    shards = getattr(leaf, "addressable_shards", None)
    if shards:
      jax.device_get([s.data for s in shards])
    else:
      jax.device_get(leaf)
