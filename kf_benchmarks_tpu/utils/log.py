"""Logging + per-step perf stats.

Keeps the reference's exact step-line format so its log-scraping tests
port over (ref: cnn_util.py:37-38 log_fn; benchmark_cnn.py:838-846 step
line; :887-902 get_perf_timing; :2351-2354 final banner).
"""

from __future__ import annotations

import math
from typing import List, Sequence


def log_fn(string: str) -> None:
  """(ref: cnn_util.py:37-38); monkey-patchable for log-scraping tests."""
  print(string, flush=True)


def get_perf_timing(batch_size: int, step_train_times: Sequence[float],
                    ewma_alpha: float = None, scale: float = 1.0):
  """images/sec mean, uncertainty, jitter (ref: benchmark_cnn.py:887-902).

  uncertainty = std(speeds)/sqrt(n); jitter = median absolute deviation
  of the per-step speeds.
  """
  times = list(step_train_times)
  if not times:
    return 0.0, 0.0, 0.0
  speeds = [batch_size / t * scale for t in times]
  n = len(speeds)
  speed_mean = scale * batch_size / (sum(times) / n)
  if n <= 1:
    return speed_mean, 0.0, 0.0
  mean_of_speeds = sum(speeds) / n
  variance = sum((s - mean_of_speeds) ** 2 for s in speeds) / n
  speed_uncertainty = math.sqrt(variance) / math.sqrt(n)
  med = sorted(speeds)[n // 2]
  speed_jitter = sorted(abs(s - med) for s in speeds)[n // 2]
  return speed_mean, speed_uncertainty, speed_jitter


def format_step_line(step: int, batch_size: int,
                     step_train_times: Sequence[float], loss: float,
                     top_1: float = None, top_5: float = None,
                     lr: float = None) -> str:
  """Per-step display line, format-compatible with the reference
  (ref: benchmark_cnn.py:834-846)."""
  speed_mean, speed_uncertainty, speed_jitter = get_perf_timing(
      batch_size, step_train_times)
  log_str = (f"{step}\timages/sec: {speed_mean:.1f} "
             f"+/- {speed_uncertainty:.1f} (jitter = {speed_jitter:.1f})\t"
             f"{loss:.3f}")
  if top_1 is not None:
    log_str += f"\t{top_1:.3f}\t{top_5:.3f}"
  if lr is not None:
    log_str += f"\t{lr:.5f}"
  return log_str


def format_total_line(images_per_sec: float) -> str:
  """The run-summary throughput line (ref: benchmark_cnn.py:2351-2354).

  Single-sourced here with the step-line format above: tests scrape
  stdout for both, and the hazard lint (analysis/lint.py rule
  'step-line-format') rejects a second copy of either literal."""
  return "total images/sec: %.2f" % images_per_sec
