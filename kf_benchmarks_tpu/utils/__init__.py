"""Shared utilities (ref: scripts/tf_cnn_benchmarks/cnn_util.py)."""
