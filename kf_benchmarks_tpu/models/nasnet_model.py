"""NASNet-A (mobile / large / cifar), TPU-native flax implementation.

Capability parity with the reference's slim NASNet stack (ref:
scripts/tf_cnn_benchmarks/models/nasnet_model.py:535-578 model classes,
:440-533 _build_nasnet_base, :248-291 _imagenet_stem/_cifar_stem,
models/nasnet_utils.py:241-491 NasNetABaseCell/NormalCell/ReductionCell).
The cell algorithm (op tables, hidden-state indices, unused-state
concatenation, factorized reduction) is re-expressed as one compact flax
module; separable convs lower to depthwise+pointwise pairs that XLA
fuses, and all shapes are static so the whole network tiles onto the MXU.

Drop-path keep-prob composes the cell-depth schedule with the
global-step ramp (ref: nasnet_utils.py:407-439): the trainer passes
``progress = step / total_training_steps`` into ``__call__`` and the
ramp scales the drop rate from 0 at step 0 to its full value at the end
of training. Without a ``progress`` argument (e.g. eval), only the
cell-depth schedule applies.

Zoph et al., "Learning Transferable Architectures for Scalable Image
Recognition" (arXiv:1707.07012).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from kf_benchmarks_tpu.models import model as model_lib
from kf_benchmarks_tpu.models.builder import BatchNorm

# NASNet-A cell op tables (ref: nasnet_utils.py:465-491).
NORMAL_OPERATIONS = (
    "separable_5x5_2", "separable_3x3_2", "separable_5x5_2",
    "separable_3x3_2", "avg_pool_3x3", "none", "avg_pool_3x3",
    "avg_pool_3x3", "separable_3x3_2", "none")
NORMAL_USED_HIDDENSTATES = (1, 0, 0, 0, 0, 0, 0)
NORMAL_HIDDENSTATE_INDICES = (0, 1, 1, 1, 0, 1, 1, 1, 0, 0)

REDUCTION_OPERATIONS = (
    "separable_5x5_2", "separable_7x7_2", "max_pool_3x3", "separable_7x7_2",
    "avg_pool_3x3", "separable_5x5_2", "none", "avg_pool_3x3",
    "separable_3x3_2", "max_pool_3x3")
REDUCTION_USED_HIDDENSTATES = (1, 1, 1, 0, 0, 0, 0)
REDUCTION_HIDDENSTATE_INDICES = (0, 1, 0, 1, 0, 1, 3, 2, 2, 0)


def calc_reduction_layers(num_cells: int,
                          num_reduction_layers: int) -> List[int]:
  """Cell indices where reduction cells go (ref: nasnet_utils.py:44-51)."""
  return [int(float(pool_num) / (num_reduction_layers + 1) * num_cells)
          for pool_num in range(1, num_reduction_layers + 1)]


def drop_path_keep_prob(base_keep_prob: float, cell_num: int,
                        total_cells: int, progress=None):
  """Keep probability after the cell-depth schedule and the global-step
  ramp (ref: nasnet_utils.py:407-439): deeper cells drop more, and the
  drop rate ramps linearly with training progress (clamped at 1) so
  early training sees keep_prob ~ 1."""
  layer_ratio = (cell_num + 1) / float(total_cells)
  keep = 1.0 - layer_ratio * (1.0 - base_keep_prob)
  if progress is not None:
    ratio = jnp.minimum(1.0, progress)
    keep = 1.0 - ratio * (1.0 - keep)
  return keep


def _op_info(operation: str) -> Tuple[int, int]:
  """'separable_5x5_2' -> (kernel=5, num_layers=2)
  (ref: nasnet_utils.py _operation_to_info)."""
  parts = operation.split("_")
  return int(parts[1].split("x")[0]), int(parts[2])


class NasnetModule(nn.Module):
  """NASNet-A network as a single compact module."""

  nclass: int
  phase_train: bool
  num_cells: int
  num_conv_filters: int
  stem_multiplier: float
  stem_type: str  # 'imagenet' | 'cifar'
  dense_dropout_keep_prob: float = 0.5
  drop_path_keep_prob: float = 1.0
  filter_scaling_rate: float = 2.0
  num_reduction_layers: int = 2
  skip_reduction_layer_input: bool = False
  use_aux_head: bool = True
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32

  # -- primitive layers -----------------------------------------------------

  def _bn(self, x):
    # slim nasnet arg_scope: decay 0.9997, eps 0.001.
    return BatchNorm(use_running_average=not self.phase_train,
                            momentum=0.9997, epsilon=1e-3, use_scale=True,
                            use_bias=True, dtype=self.dtype,
                            param_dtype=self.param_dtype)(x)

  def _conv(self, x, features, kernel, stride=1, padding="SAME"):
    return nn.Conv(features, (kernel, kernel), strides=(stride, stride),
                   padding=padding, use_bias=False, dtype=self.dtype,
                   param_dtype=self.param_dtype)(x)

  def _sep_conv_layer(self, x, features, kernel, stride):
    """Depthwise then pointwise (slim.separable_conv2d depth_multiplier=1)."""
    in_ch = x.shape[-1]
    x = nn.Conv(in_ch, (kernel, kernel), strides=(stride, stride),
                padding="SAME", feature_group_count=in_ch, use_bias=False,
                dtype=self.dtype, param_dtype=self.param_dtype)(x)
    return nn.Conv(features, (1, 1), use_bias=False, dtype=self.dtype,
                   param_dtype=self.param_dtype)(x)

  def _stacked_separable_conv(self, x, operation, filter_size, stride):
    """relu->sep->bn, twice; stride only on the first
    (ref: nasnet_utils.py:172-201)."""
    kernel, num_layers = _op_info(operation)
    for _ in range(num_layers):
      x = nn.relu(x)
      x = self._sep_conv_layer(x, filter_size, kernel, stride)
      x = self._bn(x)
      stride = 1
    return x

  def _pooling(self, x, operation, stride):
    window, strides = (3, 3), (stride, stride)
    if operation.startswith("avg"):
      return nn.avg_pool(x, window, strides, "SAME",
                         count_include_pad=False)
    return nn.max_pool(x, window, strides, "SAME")

  def _factorized_reduction(self, x, output_filters, stride):
    """Stride-2 reduction without information loss
    (ref: nasnet_utils.py:84-131)."""
    if stride == 1:
      x = self._conv(x, output_filters, 1)
      return self._bn(x)
    strides = (stride, stride)
    # 1x1-window strided pool == grid subsampling (ref uses avg_pool).
    path1 = nn.max_pool(x, (1, 1), strides, "VALID")
    path1 = self._conv(path1, output_filters // 2, 1)
    # Shift by one pixel so the second path samples the complementary grid.
    path2 = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))[:, 1:, 1:, :]
    path2 = nn.max_pool(path2, (1, 1), strides, "VALID")
    path2 = self._conv(path2, output_filters - output_filters // 2, 1)
    return self._bn(jnp.concatenate([path1, path2], axis=-1))

  def _drop_path(self, x, cell_num, total_cells, progress=None):
    """Whole-example drop with cell-depth- and progress-scaled keep prob
    (ref: nasnet_utils.py:134-145 drop_path, :406-439 schedule)."""
    if (not self.phase_train or self.drop_path_keep_prob >= 1.0 or
        cell_num < 0):
      return x
    keep_prob = jnp.asarray(drop_path_keep_prob(
        self.drop_path_keep_prob, cell_num, total_cells, progress), x.dtype)
    rng = self.make_rng("dropout")
    noise = keep_prob + jax.random.uniform(
        rng, (x.shape[0], 1, 1, 1), x.dtype)
    return x / keep_prob * jnp.floor(noise)

  # -- cell -----------------------------------------------------------------

  def _reduce_prev_layer(self, prev, curr, filter_size):
    """Match prev cell output to curr's spatial/channel dims
    (ref: nasnet_utils.py:265-282)."""
    if prev is None:
      return curr
    if prev.shape[2] != curr.shape[2]:
      prev = nn.relu(prev)
      prev = self._factorized_reduction(prev, filter_size, 2)
    elif prev.shape[-1] != filter_size:
      prev = nn.relu(prev)
      prev = self._conv(prev, filter_size, 1)
      prev = self._bn(prev)
    return prev

  def _apply_op(self, x, operation, stride, is_from_original_input,
                filter_size, cell_num, total_cells, progress=None):
    """(ref: nasnet_utils.py:350-377)."""
    if stride > 1 and not is_from_original_input:
      stride = 1
    input_filters = x.shape[-1]
    if "separable" in operation:
      x = self._stacked_separable_conv(x, operation, filter_size, stride)
    elif operation == "none":
      if stride > 1 or input_filters != filter_size:
        x = nn.relu(x)
        x = self._conv(x, filter_size, 1, stride)
        x = self._bn(x)
    elif "pool" in operation:
      x = self._pooling(x, operation, stride)
      if input_filters != filter_size:
        x = self._conv(x, filter_size, 1)
        x = self._bn(x)
    else:
      raise ValueError(f"Unimplemented operation {operation}")
    if operation != "none":
      x = self._drop_path(x, cell_num, total_cells, progress)
    return x

  def _cell(self, x, prev, operations, used_hiddenstates,
            hiddenstate_indices, filter_size, stride, cell_num, total_cells,
            progress=None):
    """One NASNet-A cell (ref: nasnet_utils.py:284-348)."""
    prev = self._reduce_prev_layer(prev, x, filter_size)
    h = nn.relu(x)
    h = self._conv(h, filter_size, 1)
    h = self._bn(h)
    states = [h, prev]
    for it in range(5):
      li, ri = hiddenstate_indices[2 * it], hiddenstate_indices[2 * it + 1]
      h1 = self._apply_op(states[li], operations[2 * it], stride, li < 2,
                          filter_size, cell_num, total_cells, progress)
      h2 = self._apply_op(states[ri], operations[2 * it + 1], stride, ri < 2,
                          filter_size, cell_num, total_cells, progress)
      states.append(h1 + h2)
    # Concat states not consumed by any combination
    # (ref: nasnet_utils.py:377-405).
    final_h, final_f = states[-1].shape[2], states[-1].shape[-1]
    outs = []
    for idx, used in enumerate(used_hiddenstates):
      if used:
        continue
      s = states[idx]
      if s.shape[2] != final_h or s.shape[-1] != final_f:
        s = self._factorized_reduction(
            s, final_f, 2 if s.shape[2] != final_h else 1)
      outs.append(s)
    return jnp.concatenate(outs, axis=-1)

  def _aux_head(self, x):
    """Auxiliary classifier (ref: nasnet_model.py:222-246)."""
    x = nn.relu(x)
    x = nn.avg_pool(x, (5, 5), (3, 3), "VALID")
    x = self._conv(x, 128, 1)
    x = self._bn(x)
    x = nn.relu(x)
    x = self._conv(x, 768, x.shape[1], padding="VALID")
    x = self._bn(x)
    x = nn.relu(x)
    x = x.reshape((x.shape[0], -1))
    return nn.Dense(self.nclass, dtype=self.dtype,
                    param_dtype=self.param_dtype)(x)

  # -- network --------------------------------------------------------------

  @nn.compact
  def __call__(self, images, progress=None):
    x = images.astype(self.dtype)
    reduction_indices = calc_reduction_layers(self.num_cells,
                                              self.num_reduction_layers)
    num_stem_cells = 2 if self.stem_type == "imagenet" else 0
    total_cells = self.num_cells + num_stem_cells + \
        self.num_reduction_layers

    # Stem (ref: nasnet_model.py:248-291).
    cell_outputs: List[Optional[jax.Array]] = [None]
    true_cell_num = 0
    if self.stem_type == "imagenet":
      x = self._conv(x, int(32 * self.stem_multiplier), 3, 2,
                     padding="VALID")
      x = self._bn(x)
      cell_outputs.append(x)
      filter_scaling = 1.0 / (self.filter_scaling_rate ** num_stem_cells)
      for _ in range(num_stem_cells):
        x = self._cell(
            x, cell_outputs[-2], REDUCTION_OPERATIONS,
            REDUCTION_USED_HIDDENSTATES, REDUCTION_HIDDENSTATE_INDICES,
            int(self.num_conv_filters * filter_scaling), 2, true_cell_num,
            total_cells, progress)
        cell_outputs.append(x)
        filter_scaling *= self.filter_scaling_rate
        true_cell_num += 1
    else:
      x = self._conv(x, int(self.num_conv_filters * self.stem_multiplier), 3)
      x = self._bn(x)
      cell_outputs.append(x)

    aux_head_cell_idx = (reduction_indices[1] - 1
                         if len(reduction_indices) >= 2 else -1)
    aux_logits = None
    filter_scaling = 1.0
    for cell_num in range(self.num_cells):
      if self.skip_reduction_layer_input:
        prev_layer = cell_outputs[-2]
      if cell_num in reduction_indices:
        filter_scaling *= self.filter_scaling_rate
        x = self._cell(
            x, cell_outputs[-2], REDUCTION_OPERATIONS,
            REDUCTION_USED_HIDDENSTATES, REDUCTION_HIDDENSTATE_INDICES,
            int(self.num_conv_filters * filter_scaling), 2, true_cell_num,
            total_cells, progress)
        true_cell_num += 1
        cell_outputs.append(x)
      if not self.skip_reduction_layer_input:
        prev_layer = cell_outputs[-2]
      x = self._cell(
          x, prev_layer, NORMAL_OPERATIONS, NORMAL_USED_HIDDENSTATES,
          NORMAL_HIDDENSTATE_INDICES,
          int(self.num_conv_filters * filter_scaling), 1, true_cell_num,
          total_cells, progress)
      true_cell_num += 1
      if (self.use_aux_head and cell_num == aux_head_cell_idx and
          self.phase_train):
        aux_logits = self._aux_head(x)
      cell_outputs.append(x)

    x = nn.relu(x)
    x = jnp.mean(x, axis=(1, 2))
    if self.phase_train and self.dense_dropout_keep_prob < 1.0:
      x = nn.Dropout(rate=1.0 - self.dense_dropout_keep_prob,
                     deterministic=False)(x)
    logits = nn.Dense(self.nclass, dtype=self.dtype,
                      param_dtype=self.param_dtype)(x)
    logits = logits.astype(jnp.float32)
    if aux_logits is not None:
      aux_logits = aux_logits.astype(jnp.float32)
    return logits, aux_logits


class _NasnetBase(model_lib.CNNModel):
  """Shared make_module plumbing for the three NASNet configs."""

  _MODULE_KW: dict = {}

  def skip_final_affine_layer(self):
    return True

  def make_module(self, nclass, phase_train, data_format="NHWC",
                  dtype=jnp.float32, param_dtype=jnp.float32):
    del data_format  # NHWC throughout
    return NasnetModule(nclass=nclass, phase_train=phase_train,
                        dtype=dtype, param_dtype=param_dtype,
                        **self._MODULE_KW)


class NasnetModel(_NasnetBase):
  """NASNet-A mobile (ref: nasnet_model.py:535-547; hparams :96-108)."""

  _MODULE_KW = dict(num_cells=12, num_conv_filters=44, stem_multiplier=1.0,
                    stem_type="imagenet", dense_dropout_keep_prob=0.5,
                    drop_path_keep_prob=1.0)

  def __init__(self, params=None):
    super().__init__("nasnet", 224, 32, 0.005, params=params)


class NasnetLargeModel(_NasnetBase):
  """NASNet-A large (ref: nasnet_model.py:550-563; hparams :68-81)."""

  _MODULE_KW = dict(num_cells=18, num_conv_filters=168, stem_multiplier=3.0,
                    stem_type="imagenet", dense_dropout_keep_prob=0.5,
                    drop_path_keep_prob=0.7, skip_reduction_layer_input=True)

  def __init__(self, params=None):
    super().__init__("nasnet", 331, 16, 0.005, params=params)


class NasnetCifarModel(_NasnetBase):
  """NASNet-A cifar (ref: nasnet_model.py:566-578; hparams :36-50)."""

  _MODULE_KW = dict(num_cells=18, num_conv_filters=32, stem_multiplier=3.0,
                    stem_type="cifar", dense_dropout_keep_prob=1.0,
                    drop_path_keep_prob=0.6)

  def __init__(self, params=None):
    super().__init__("nasnet", 32, 32, 0.025, params=params)


def create_nasnet_model(params=None):
  return NasnetModel(params=params)


def create_nasnetlarge_model(params=None):
  return NasnetLargeModel(params=params)


def create_nasnet_cifar_model(params=None):
  return NasnetCifarModel(params=params)
