"""VGG model configurations (ref: models/vgg_model.py).

vgg11/vgg16/vgg19 are models A, D, E from Simonyan & Zisserman,
"Very Deep Convolutional Networks for Large-Scale Image Recognition"
(arXiv:1409.1556).
"""

from kf_benchmarks_tpu.models import model


def _construct_vgg(cnn, num_conv_layers):
  """Five conv blocks with doubling widths, then two FC+dropout stages
  (ref: models/vgg_model.py:30-52)."""
  assert len(num_conv_layers) == 5
  for channels, count in zip((64, 128, 256, 512, 512), num_conv_layers):
    for _ in range(count):
      cnn.conv(channels, 3, 3)
    cnn.mpool(2, 2)
  cnn.reshape([-1, 512 * 7 * 7])
  cnn.affine(4096)
  cnn.dropout()
  cnn.affine(4096)
  cnn.dropout()


class Vgg11Model(model.CNNModel):
  """(ref: models/vgg_model.py:55-62)"""

  def __init__(self, params=None):
    super().__init__("vgg11", 224, 64, 0.005, params=params)

  def add_inference(self, cnn):
    _construct_vgg(cnn, [1, 1, 2, 2, 2])


class Vgg16Model(model.CNNModel):
  """(ref: models/vgg_model.py:65-71)"""

  def __init__(self, params=None):
    super().__init__("vgg16", 224, 64, 0.005, params=params)

  def add_inference(self, cnn):
    _construct_vgg(cnn, [2, 2, 3, 3, 3])


class Vgg19Model(model.CNNModel):
  """(ref: models/vgg_model.py:74-80)"""

  def __init__(self, params=None):
    super().__init__("vgg19", 224, 64, 0.005, params=params)

  def add_inference(self, cnn):
    _construct_vgg(cnn, [2, 2, 4, 4, 4])
