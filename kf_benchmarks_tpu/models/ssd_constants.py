"""SSD300 constants (ref: scripts/tf_cnn_benchmarks/ssd_constants.py).

Hyperparameters of the MLPerf single-stage detector reference: SSD300
with a modified ResNet-34 backbone on COCO. Values are the public MLPerf
constants (anchor scales per ssd.pytorch, normalization per
torchvision).
"""

IMAGE_SIZE = 300

# 81 including the background class 0; not all COCO ids are used.
NUM_CLASSES = 81

# COCO category id <-> contiguous label mapping (ref: ssd_constants.py:31-39).
CLASS_INV_MAP = (
    0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 13, 14, 15, 16, 17, 18, 19, 20, 21,
    22, 23, 24, 25, 27, 28, 31, 32, 33, 34, 35, 36, 37, 38, 39, 40, 41, 42,
    43, 44, 46, 47, 48, 49, 50, 51, 52, 53, 54, 55, 56, 57, 58, 59, 60, 61,
    62, 63, 64, 65, 67, 70, 72, 73, 74, 75, 76, 77, 78, 79, 80, 81, 82, 84,
    85, 86, 87, 88, 89, 90)
_MAP = {j: i for i, j in enumerate(CLASS_INV_MAP)}  # local helper
CLASS_MAP = tuple(_MAP.get(i, -1) for i in range(max(CLASS_INV_MAP) + 1))

NUM_SSD_BOXES = 8732

RESNET_DEPTH = 34

MIN_LEVEL = 3
MAX_LEVEL = 8

FEATURE_SIZES = (38, 19, 10, 5, 3, 1)
STEPS = (8, 16, 32, 64, 100, 300)
SCALES = (21, 45, 99, 153, 207, 261, 315)
ASPECT_RATIOS = ((2,), (2, 3), (2, 3), (2, 3), (2,), (2,))
NUM_DEFAULTS = (4, 6, 6, 6, 4, 4)
SCALE_XY = 0.1
SCALE_HW = 0.2
BOX_CODER_SCALES = (1 / SCALE_XY, 1 / SCALE_XY, 1 / SCALE_HW, 1 / SCALE_HW)
MATCH_THRESHOLD = 0.5

NORMALIZATION_MEAN = (0.485, 0.456, 0.406)
NORMALIZATION_STD = (0.229, 0.224, 0.225)

# SSD cropping (ref: ssd_crop, ssd_dataloader.py:114-228)
NUM_CROP_PASSES = 50
CROP_MIN_IOU_CHOICES = (0, 0.1, 0.3, 0.5, 0.7, 0.9)
P_NO_CROP_PER_PASS = 1 / (len(CROP_MIN_IOU_CHOICES) + 1)

# Hard example mining
NEGS_PER_POSITIVE = 3

BATCH_NORM_DECAY = 0.997
BATCH_NORM_EPSILON = 1e-4

# MLPerf reference LR schedule (base batch 32)
LEARNING_RATE_SCHEDULE = (
    (0, 1e-3),
    (160000, 1e-4),
    (200000, 1e-5),
)
MOMENTUM = 0.9
WEIGHT_DECAY = 5e-4

CHECKPOINT_FREQUENCY = 20000
MAX_NUM_EVAL_BOXES = 200
OVERLAP_CRITERIA = 0.5  # NMS IoU threshold
MIN_SCORE = 0.05
DUMMY_SCORE = -1e5

ANNOTATION_FILE = "annotations/instances_val2017.json"
COCO_NUM_VAL_IMAGES = 4952
