"""GoogLeNet model configuration (ref: models/googlenet_model.py).

Szegedy et al., "Going deeper with convolutions" (arXiv:1409.4842).
"""

from kf_benchmarks_tpu.models import model


class GooglenetModel(model.CNNModel):
  """(ref: models/googlenet_model.py:27-59)"""

  def __init__(self, params=None):
    super().__init__("googlenet", 224, 32, 0.005, params=params)

  def add_inference(self, cnn):
    def inception_v1(cnn, k, l, m, n, p, q):
      cols = [[("conv", k, 1, 1)],
              [("conv", l, 1, 1), ("conv", m, 3, 3)],
              [("conv", n, 1, 1), ("conv", p, 5, 5)],
              [("mpool", 3, 3, 1, 1, "SAME"), ("conv", q, 1, 1)]]
      cnn.inception_module("incept_v1", cols)

    cnn.conv(64, 7, 7, 2, 2)
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    cnn.conv(64, 1, 1)
    cnn.conv(192, 3, 3)
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    inception_v1(cnn, 64, 96, 128, 16, 32, 32)
    inception_v1(cnn, 128, 128, 192, 32, 96, 64)
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    inception_v1(cnn, 192, 96, 208, 16, 48, 64)
    inception_v1(cnn, 160, 112, 224, 24, 64, 64)
    inception_v1(cnn, 128, 128, 256, 24, 64, 64)
    inception_v1(cnn, 112, 144, 288, 32, 64, 64)
    inception_v1(cnn, 256, 160, 320, 32, 128, 128)
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    inception_v1(cnn, 256, 160, 320, 32, 128, 128)
    inception_v1(cnn, 384, 192, 384, 48, 128, 128)
    cnn.apool(7, 7, 1, 1, mode="VALID")
    cnn.reshape([-1, 1024])
