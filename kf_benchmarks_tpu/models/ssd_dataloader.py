"""SSD anchor generation + target assignment + box coding.

TPU-native re-design of the reference's ssd_dataloader
(ref: scripts/tf_cnn_benchmarks/ssd_dataloader.py:35-112 DefaultBoxes +
IoU; :257-320 encode_labels via the object_detection lib's
target assigner). Anchors are generated once in numpy at build time (a
static constant XLA folds into the program); matching/encoding is pure
numpy on the host input path, and decoding is jnp so eval can run
jitted.

Ordering note: anchors, head outputs, and targets all use
location-major order (feature map -> grid (i, j) -> default box), the
order DefaultBoxes itself produces. The reference's model flattens its
NCHW head outputs defaults-major (ssd_model.py:190-210), which disagrees
with its own anchor order; we keep the two consistent instead of
reproducing the quirk.
"""

from __future__ import annotations

import itertools
import math
from typing import Tuple

import numpy as np
import jax.numpy as jnp

from kf_benchmarks_tpu.models import ssd_constants


class DefaultBoxes:
  """The 8732 SSD300 anchors (ref: ssd_dataloader.py:35-79)."""

  def __init__(self):
    fk = ssd_constants.IMAGE_SIZE / np.array(ssd_constants.STEPS)
    boxes = []
    for idx, feature_size in enumerate(ssd_constants.FEATURE_SIZES):
      sk1 = ssd_constants.SCALES[idx] / ssd_constants.IMAGE_SIZE
      sk2 = ssd_constants.SCALES[idx + 1] / ssd_constants.IMAGE_SIZE
      sk3 = math.sqrt(sk1 * sk2)
      all_sizes = [(sk1, sk1), (sk3, sk3)]
      for alpha in ssd_constants.ASPECT_RATIOS[idx]:
        w, h = sk1 * math.sqrt(alpha), sk1 / math.sqrt(alpha)
        all_sizes.append((w, h))
        all_sizes.append((h, w))
      assert len(all_sizes) == ssd_constants.NUM_DEFAULTS[idx]
      for i, j in itertools.product(range(feature_size), repeat=2):
        cx, cy = (j + 0.5) / fk[idx], (i + 0.5) / fk[idx]
        for w, h in all_sizes:
          boxes.append((cy, cx, h, w))
    assert len(boxes) == ssd_constants.NUM_SSD_BOXES
    self.default_boxes_cychw = np.clip(
        np.asarray(boxes, np.float32), 0.0, 1.0)

  def __call__(self, order: str = "ltrb") -> np.ndarray:
    """[N, 4] anchors; 'ltrb' = (ymin, xmin, ymax, xmax), 'xywh' =
    (cy, cx, h, w)."""
    if order == "xywh":
      return self.default_boxes_cychw
    cy, cx, h, w = np.split(self.default_boxes_cychw, 4, axis=-1)
    return np.concatenate(
        [cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2], axis=-1)


def calc_iou_matrix(boxes1: np.ndarray, boxes2: np.ndarray) -> np.ndarray:
  """Pairwise IoU of [N,4] x [M,4] ltrb boxes (ref: calc_iou_tensor,
  ssd_dataloader.py:81-112)."""
  b1 = boxes1[:, None, :]
  b2 = boxes2[None, :, :]
  tl = np.maximum(b1[..., :2], b2[..., :2])
  br = np.minimum(b1[..., 2:], b2[..., 2:])
  wh = np.clip(br - tl, 0.0, None)
  inter = wh[..., 0] * wh[..., 1]
  area1 = ((boxes1[:, 2] - boxes1[:, 0]) *
           (boxes1[:, 3] - boxes1[:, 1]))[:, None]
  area2 = ((boxes2[:, 2] - boxes2[:, 0]) *
           (boxes2[:, 3] - boxes2[:, 1]))[None, :]
  return inter / np.clip(area1 + area2 - inter, 1e-12, None)


def encode_boxes(boxes_cychw: np.ndarray,
                 anchors_cychw: np.ndarray) -> np.ndarray:
  """Faster-RCNN box coding with SSD scales (ref: encode_labels's
  FasterRcnnBoxCoder scale_factors, ssd_dataloader.py:273-289)."""
  scales = np.asarray(ssd_constants.BOX_CODER_SCALES, np.float32)
  ty = (boxes_cychw[..., 0] - anchors_cychw[..., 0]) / anchors_cychw[..., 2]
  tx = (boxes_cychw[..., 1] - anchors_cychw[..., 1]) / anchors_cychw[..., 3]
  th = np.log(np.clip(boxes_cychw[..., 2], 1e-8, None) /
              anchors_cychw[..., 2])
  tw = np.log(np.clip(boxes_cychw[..., 3], 1e-8, None) /
              anchors_cychw[..., 3])
  return np.stack([ty * scales[0], tx * scales[1],
                   th * scales[2], tw * scales[3]], axis=-1)


def decode_boxes(encoded, anchors_cychw):
  """Inverse of encode_boxes, in jnp so eval decoding stays jitted.
  Returns ltrb boxes."""
  scales = jnp.asarray(ssd_constants.BOX_CODER_SCALES, jnp.float32)
  anchors = jnp.asarray(anchors_cychw)
  cy = encoded[..., 0] / scales[0] * anchors[..., 2] + anchors[..., 0]
  cx = encoded[..., 1] / scales[1] * anchors[..., 3] + anchors[..., 1]
  h = jnp.exp(encoded[..., 2] / scales[2]) * anchors[..., 2]
  w = jnp.exp(encoded[..., 3] / scales[3]) * anchors[..., 3]
  return jnp.stack([cy - h / 2, cx - w / 2, cy + h / 2, cx + w / 2],
                   axis=-1)


def _ltrb_to_cychw(boxes: np.ndarray) -> np.ndarray:
  ymin, xmin, ymax, xmax = np.split(boxes, 4, axis=-1)
  return np.concatenate([(ymin + ymax) / 2, (xmin + xmax) / 2,
                         ymax - ymin, xmax - xmin], axis=-1)


def encode_labels(gt_boxes: np.ndarray, gt_labels: np.ndarray,
                  default_boxes: DefaultBoxes = None
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
  """Assign ground truth to anchors and encode regression targets
  (ref: encode_labels, ssd_dataloader.py:257-320).

  Args:
    gt_boxes: [M, 4] ltrb in [0, 1].
    gt_labels: [M] int class ids (contiguous, 1-based; 0 = background).
  Returns:
    (encoded_boxes [N,4], classes [N], num_matched scalar): anchors with
    IoU >= MATCH_THRESHOLD against some gt box get that box's encoded
    coordinates and label; the rest are background (class 0).
  """
  db = default_boxes or _default_boxes_singleton()
  anchors_ltrb = db("ltrb")
  anchors_cychw = db("xywh")
  n = anchors_ltrb.shape[0]
  classes = np.zeros((n,), np.int32)
  encoded = np.zeros((n, 4), np.float32)
  if gt_boxes.shape[0] == 0:
    return encoded, classes, np.float32(1.0)
  iou = calc_iou_matrix(anchors_ltrb, gt_boxes.astype(np.float32))
  best_gt = iou.argmax(axis=1)
  best_iou = iou.max(axis=1)
  matched = best_iou >= ssd_constants.MATCH_THRESHOLD
  # Every gt box claims its best anchor even below threshold (standard
  # SSD bipartite step, as in the object_detection target assigner).
  forced = iou.argmax(axis=0)
  matched[forced] = True
  best_gt[forced] = np.arange(gt_boxes.shape[0])
  classes[matched] = gt_labels[best_gt[matched]].astype(np.int32)
  gt_cychw = _ltrb_to_cychw(gt_boxes.astype(np.float32))
  encoded[matched] = encode_boxes(gt_cychw[best_gt[matched]],
                                  anchors_cychw[matched])
  return encoded, classes, np.float32(max(matched.sum(), 1))


_SINGLETON = None


def _default_boxes_singleton() -> DefaultBoxes:
  global _SINGLETON
  if _SINGLETON is None:
    _SINGLETON = DefaultBoxes()
  return _SINGLETON
