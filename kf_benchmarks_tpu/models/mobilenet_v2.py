"""MobileNet v2, TPU-native flax implementation.

Capability parity with the reference's slim op-spec MobileNet stack
(ref: scripts/tf_cnn_benchmarks/models/mobilenet.py op-spec interpreter,
models/conv_blocks.py expanded_conv, models/mobilenet_v2.py:42-78 V2_DEF
+ :188-198 MobilenetModel). The reference drives a generic slim
``arg_scope`` interpreter over an op list; here the same architecture
table (`V2_DEF`) is interpreted directly into flax submodules inside one
compact module, so XLA sees a single fusable graph. Inverted-residual
blocks keep depthwise convs in NHWC, the layout the TPU vector unit
wants.

Sandler et al., "MobileNetV2: Inverted Residuals and Linear Bottlenecks"
(arXiv:1801.04381).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from kf_benchmarks_tpu.models import model as model_lib
from kf_benchmarks_tpu.models.builder import BatchNorm


def make_divisible(v: float, divisor: int = 8,
                   min_value: Optional[int] = None) -> int:
  """Round channel counts to a multiple of ``divisor`` without dropping
  more than 10% (ref: mobilenet.py _make_divisible)."""
  if min_value is None:
    min_value = divisor
  new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
  if new_v < 0.9 * v:
    new_v += divisor
  return new_v


@dataclasses.dataclass(frozen=True)
class OpSpec:
  """One row of the architecture table (ref: mobilenet_v2.py:42-78 ``op``
  entries): 'conv' is a full conv, 'expanded_conv' an inverted-residual
  bottleneck with the given expansion factor."""
  op: str
  num_outputs: int
  stride: int = 1
  expansion: int = 6
  kernel: int = 3


# ref: mobilenet_v2.py:56-79 V2_DEF['spec']
V2_DEF: Tuple[OpSpec, ...] = (
    OpSpec("conv", 32, stride=2),
    OpSpec("expanded_conv", 16, expansion=1),
    OpSpec("expanded_conv", 24, stride=2),
    OpSpec("expanded_conv", 24),
    OpSpec("expanded_conv", 32, stride=2),
    OpSpec("expanded_conv", 32),
    OpSpec("expanded_conv", 32),
    OpSpec("expanded_conv", 64, stride=2),
    OpSpec("expanded_conv", 64),
    OpSpec("expanded_conv", 64),
    OpSpec("expanded_conv", 64),
    OpSpec("expanded_conv", 96),
    OpSpec("expanded_conv", 96),
    OpSpec("expanded_conv", 96),
    OpSpec("expanded_conv", 160, stride=2),
    OpSpec("expanded_conv", 160),
    OpSpec("expanded_conv", 160),
    OpSpec("expanded_conv", 320),
    OpSpec("conv", 1280, kernel=1),
)


class MobilenetV2Module(nn.Module):
  """Interprets V2_DEF into an inverted-residual network + classifier."""

  nclass: int
  phase_train: bool
  depth_multiplier: float = 1.0
  dropout_keep_prob: float = 0.8
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32

  def _bn(self, x):
    # slim defaults the reference trains with: decay 0.997, eps 0.001
    # (ref: mobilenet.py training_scope).
    return BatchNorm(
        use_running_average=not self.phase_train, momentum=0.997,
        epsilon=1e-3, use_scale=True, use_bias=True,
        dtype=self.dtype, param_dtype=self.param_dtype)(x)

  def _conv(self, x, features, kernel, stride, groups=1):
    return nn.Conv(
        features, (kernel, kernel), strides=(stride, stride),
        padding="SAME", use_bias=False, feature_group_count=groups,
        dtype=self.dtype, param_dtype=self.param_dtype)(x)

  def _depth(self, channels: int) -> int:
    return make_divisible(channels * self.depth_multiplier)

  @nn.compact
  def __call__(self, images):
    x = images.astype(self.dtype)
    for i, spec in enumerate(V2_DEF):
      if spec.op == "conv":
        out = self._depth(spec.num_outputs)
        x = self._conv(x, out, spec.kernel, spec.stride)
        x = self._bn(x)
        x = nn.relu6(x)
      else:
        inp = x.shape[-1]
        out = self._depth(spec.num_outputs)
        h = x
        expanded = inp * spec.expansion
        if spec.expansion != 1:
          h = self._conv(h, expanded, 1, 1)
          h = self._bn(h)
          h = nn.relu6(h)
        # Depthwise 3x3 (feature_group_count == channels).
        h = self._conv(h, expanded, spec.kernel, spec.stride,
                       groups=expanded)
        h = self._bn(h)
        h = nn.relu6(h)
        # Linear bottleneck projection: no activation (ref:
        # conv_blocks.py expanded_conv projection).
        h = self._conv(h, out, 1, 1)
        h = self._bn(h)
        if spec.stride == 1 and out == inp:
          h = h + x
        x = h
    # Global pool + dropout + 1x1-conv classifier
    # (ref: mobilenet.py mobilenet() top).
    x = jnp.mean(x, axis=(1, 2))
    if self.phase_train and self.dropout_keep_prob < 1.0:
      x = nn.Dropout(rate=1.0 - self.dropout_keep_prob,
                     deterministic=False)(x)
    logits = nn.Dense(self.nclass, dtype=self.dtype,
                      param_dtype=self.param_dtype)(x)
    return logits.astype(jnp.float32), None


class MobilenetModel(model_lib.CNNModel):
  """Mobilenet model configuration (ref: mobilenet_v2.py:188-198)."""

  def __init__(self, params=None, depth_multiplier: float = 1.0):
    super().__init__("mobilenet", 224, 32, 0.005, params=params)
    self.depth_multiplier = depth_multiplier

  def skip_final_affine_layer(self):
    return True

  def make_module(self, nclass, phase_train, data_format="NHWC",
                  dtype=jnp.float32, param_dtype=jnp.float32):
    del data_format  # NHWC throughout; NCHW inputs not supported here
    return MobilenetV2Module(
        nclass=nclass, phase_train=phase_train,
        depth_multiplier=self.depth_multiplier,
        dtype=dtype, param_dtype=param_dtype)


def create_mobilenet_model(params=None):
  return MobilenetModel(params=params)
