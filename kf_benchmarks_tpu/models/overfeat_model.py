"""Overfeat model configuration (ref: models/overfeat_model.py).

Sermanet et al., "OverFeat: Integrated Recognition, Localization and
Detection using Convolutional Networks" (arXiv:1312.6229).
"""

from kf_benchmarks_tpu.models import model


class OverfeatModel(model.CNNModel):
  """(ref: models/overfeat_model.py:28-50)"""

  def __init__(self, params=None):
    super().__init__("overfeat", 231, 32, 0.005, params=params)

  def add_inference(self, cnn):
    cnn.conv(96, 11, 11, 4, 4, mode="VALID")
    cnn.mpool(2, 2)
    cnn.conv(256, 5, 5, 1, 1, mode="VALID")
    cnn.mpool(2, 2)
    cnn.conv(512, 3, 3)
    cnn.conv(1024, 3, 3)
    cnn.conv(1024, 3, 3)
    cnn.mpool(2, 2)
    cnn.reshape([-1, 1024 * 6 * 6])
    cnn.affine(3072)
    cnn.dropout()
    cnn.affine(4096)
    cnn.dropout()
