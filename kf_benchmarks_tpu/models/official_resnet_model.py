"""Official-models ResNet wrapper analog: sizes 18-200, versions 1/2.

The reference wraps tf-models-official's ImagenetModel (ref:
scripts/tf_cnn_benchmarks/models/official_resnet_model.py:26-77,
requiring the models repo on PYTHONPATH); here the same size/version
matrix is served natively: basic residual blocks for 18/34, bottleneck
blocks for 50/101/152/200, sharing the local builder blocks
(resnet_model.residual_block / bottleneck_block) -- no external
dependency.
"""

from __future__ import annotations

from kf_benchmarks_tpu.models import model as model_lib
from kf_benchmarks_tpu.models import resnet_model

# size -> (block kind, per-stage counts) (the official _get_block_sizes)
_RESNET_SIZES = {
    18: ("basic", (2, 2, 2, 2)),
    34: ("basic", (3, 4, 6, 3)),
    50: ("bottleneck", (3, 4, 6, 3)),
    101: ("bottleneck", (3, 4, 23, 3)),
    152: ("bottleneck", (3, 8, 36, 3)),
    200: ("bottleneck", (3, 24, 36, 3)),
}


class OfficialResnetModel(model_lib.CNNModel):
  """(ref: official_resnet_model.py:26-77)."""

  def __init__(self, resnet_size: int = 50, version: int = 1, params=None):
    if resnet_size not in _RESNET_SIZES:
      raise ValueError(
          f"resnet_size must be one of {sorted(_RESNET_SIZES)}, got "
          f"{resnet_size}")
    if version not in (1, 2):
      raise ValueError(f"version must be 1 or 2, got {version}")
    self.resnet_size = resnet_size
    self.block_kind, self.block_counts = _RESNET_SIZES[resnet_size]
    # tf-models-official's "v1" strides on the 3x3 conv inside the
    # bottleneck (the v1.5 arrangement in this codebase's block
    # terminology), not the original-paper 1x1 stride.
    self.version = "v1.5" if version == 1 else "v2"
    super().__init__(f"official_resnet{resnet_size}_v{version}", 224, 32,
                     0.1, params=params)

  def add_inference(self, cnn):
    cnn.use_batch_norm = self.version != "v2"
    cnn.batch_norm_config = {"decay": 0.9, "epsilon": 1e-5, "scale": True}
    cnn.conv(64, 7, 7, 2, 2, mode="SAME_RESNET",
             use_batch_norm=(self.version != "v2"), activation="relu",
             bias=None, name="conv_stem")
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    if self.block_kind == "basic":
      for i, (count, depth) in enumerate(
          zip(self.block_counts, (64, 128, 256, 512))):
        for j in range(count):
          stride = 2 if (j == 0 and i > 0) else 1
          resnet_model.residual_block(cnn, depth, stride, self.version)
    else:
      for i, (count, depth_bottleneck, depth) in enumerate(
          zip(self.block_counts, (64, 128, 256, 512),
              (256, 512, 1024, 2048))):
        for j in range(count):
          stride = 2 if (j == 0 and i > 0) else 1
          resnet_model.bottleneck_block(cnn, depth, depth_bottleneck,
                                        stride, self.version)
    if self.version == "v2":
      cnn.batch_norm(name="final_bn")
      import flax.linen as nn
      cnn.top_layer = nn.relu(cnn.top_layer)
    cnn.spatial_mean()

  def get_learning_rate(self, global_step, batch_size):
    """Piecewise [30, 60, 80, 90] with warmup, as the official wrapper
    configures (ref: official_resnet_model.py:50-59) -- same schedule as
    the local ResnetModel."""
    return resnet_model.ResnetModel.get_learning_rate(
        self, global_step, batch_size)


def create_official_resnet18_model(params=None):
  return OfficialResnetModel(18, 1, params=params)


def create_official_resnet34_model(params=None):
  return OfficialResnetModel(34, 1, params=params)


def create_official_resnet50_model(params=None):
  return OfficialResnetModel(50, 1, params=params)


def create_official_resnet50_v2_model(params=None):
  return OfficialResnetModel(50, 2, params=params)


def create_official_resnet101_model(params=None):
  return OfficialResnetModel(101, 1, params=params)


def create_official_resnet152_model(params=None):
  return OfficialResnetModel(152, 1, params=params)


def create_official_resnet200_model(params=None):
  return OfficialResnetModel(200, 1, params=params)
