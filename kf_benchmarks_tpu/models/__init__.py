"""Model zoo + layer builder (ref: scripts/tf_cnn_benchmarks/models/)."""
