"""LeNet-5 model configuration (ref: models/lenet_model.py).

Matches the TF MNIST-tutorial variant the reference uses.
"""

from kf_benchmarks_tpu.models import model


class Lenet5Model(model.CNNModel):
  """(ref: models/lenet_model.py:27-40)"""

  def __init__(self, params=None):
    super().__init__("lenet5", 28, 32, 0.005, params=params)

  def add_inference(self, cnn):
    cnn.conv(32, 5, 5)
    cnn.mpool(2, 2)
    cnn.conv(64, 5, 5)
    cnn.mpool(2, 2)
    cnn.reshape([-1, 64 * 7 * 7])
    cnn.affine(512)
