"""Transformer language model -- the zoo's long-context family.

BEYOND-REFERENCE: the reference zoo (ref: scripts/tf_cnn_benchmarks/
models/model_config.py:38-142) has no transformer/LM family; this model
makes the framework's long-context machinery reachable through the
stock CLI like any other zoo member:

    python -m kf_benchmarks_tpu.cli --model=transformer_lm \
        --batch_size=8 --use_fp16=true

A GPT-style decoder-only LM (pre-LN blocks, learned positions) whose
attention core is ``parallel/sequence.blockwise_attention`` -- the
flash-style online-softmax schedule measured in PERF.md (exact causal
attention at 64k tokens on one 16 GB chip, 2-4x faster than
materialised-score attention at every length). Synthetic data follows
the NCF/DeepSpeech pattern: int32 token ids ride the feature slot,
next-token ids the label slot; throughput prints as sequences/sec on
the standard step line (x seq_len for tokens/sec).

HBM footprint (the round-7 pass; PERF.md):

* The L identical blocks run as ONE scanned layer (nn.scan) with
  ``jax.checkpoint`` per block (nn.remat), so the compiled program
  carries one block body instead of L copies and the backward pass
  keeps one block-boundary residual per layer instead of every
  intermediate.
* The LM head never materializes the (B, T, V) logits tensor: the
  module returns ``ops.fused_loss.FusedLMHead`` (final hidden states +
  unembedding kernel) and the loss/accuracy functions reduce it chunk
  at a time (peak temp O(B*chunk*V); bit-exact against the monolithic
  head, tests/test_fused_loss.py).

Both levers are env-switchable for on-chip A/Bs:
KF_TRANSFORMER_LM_HEAD in ('fused', 'dense'),
KF_TRANSFORMER_LM_LAYERS in ('scan', 'loop').
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn

from kf_benchmarks_tpu.models import model as model_lib
from kf_benchmarks_tpu.ops import fused_loss as fused_loss_lib
from kf_benchmarks_tpu.parallel import sequence as sequence_lib

VOCAB = 32768
SEQ_LEN = 2048
D_MODEL = 512
N_LAYERS = 6
N_HEADS = 8
D_FF = 2048
ATTN_BLOCK = 512
# Two-level (q x kv) tiling: accumulators stay q-block-sized instead of
# full-length, and causal runs skip strictly-future K/V blocks.
ATTN_Q_BLOCK = 512


class _Block(nn.Module):
  """One pre-LN decoder block; the unit nn.scan stacks L-fold.

  The (carry, None) -> (carry, None) signature is the nn.scan contract;
  the loop fallback calls it with the same shape so the two layer
  paths share one body (and therefore cannot drift numerically).
  """
  d_model: int
  n_heads: int
  d_ff: int
  attn_block: int
  attn_q_block: int
  attn_impl: str
  dtype: Any
  param_dtype: Any
  # Serving (kf_benchmarks_tpu/serving/): decode=True switches the
  # block to the single-token KV-ring path -- carry (x (B,1,D), pos
  # (B,)), scanned input/output = this layer's (k, v) ring buffers.
  # return_kv=True makes the TRAINING/forward branch also emit its
  # per-position K/V projections as scan outputs (the packed-prefill
  # cache source). Both default off, so the training program -- and
  # every golden contract -- is untouched. decode_exact routes the
  # decode attention through the full-sequence op graph (the
  # bit-identity oracle mode; sequence.decode_attention).
  decode: bool = False
  return_kv: bool = False
  decode_exact: bool = False
  # Paged KV cache (serving/decode.py paged mode): >0 switches the
  # decode branch's per-layer cache from a (B, T, H, Dh) ring slab to a
  # shared (P, page, H, Dh) page POOL -- the carry additionally rides
  # the (B, pages_per_slot) page table, writes scatter into the pool
  # row the table maps pos's page to, and attention gathers pages
  # (sequence.decode_attention page_table mode). 0 = the dense ring
  # (every existing program unchanged).
  kv_page_size: int = 0

  @nn.compact
  def __call__(self, carry, xs):
    dense = lambda feats, name, bias=True: nn.Dense(
        feats, use_bias=bias, name=name, dtype=self.dtype,
        param_dtype=self.param_dtype)
    # LayerNorm computes in f32 (bf16 mean/variance loses too much);
    # the surrounding denses cast back down.
    ln = lambda name: nn.LayerNorm(name=name, dtype=jnp.float32,
                                   param_dtype=self.param_dtype)
    head_dim = self.d_model // self.n_heads
    if self.decode and self.kv_page_size:
      # Paged single-token decode: this layer's cache is the shared
      # (P, page, H, Dh) pool; the slot's page table (carry) maps its
      # logical page for ``pos`` to a pool row. Same submodules as the
      # dense branch; the write is a batched scatter at (table[b,
      # pos//page], pos%page) -- inactive/completed slots carry an
      # all-zero table row, so their writes land on pool row 0, the
      # engine's never-allocated scratch page (serving/engine.py).
      x, pos, table = carry
      ck, cv = xs
      b = x.shape[0]
      page = self.kv_page_size
      t_logical = table.shape[1] * page
      h = ln("ln1")(x).astype(self.dtype)
      qkv = dense(3 * self.d_model, "qkv", bias=False)(h)
      qkv = qkv.reshape(b, 1, 3, self.n_heads, head_dim)
      rpos = pos % t_logical
      pg = jnp.take_along_axis(table, (rpos // page)[:, None],
                               axis=1)[:, 0]                   # (B,)
      ck = ck.at[pg, rpos % page].set(qkv[:, 0, 1])
      cv = cv.at[pg, rpos % page].set(qkv[:, 0, 2])
      att = sequence_lib.decode_attention(
          qkv[:, :, 0], ck, cv, pos, block=page,
          impl=self.attn_impl, exact=self.decode_exact,
          q_block=page, page_table=table)
      x = x + dense(self.d_model, "attn_out")(
          att.reshape(b, 1, self.d_model))
      h = ln("ln2")(x).astype(self.dtype)
      h = nn.gelu(dense(self.d_ff, "mlp_up")(h))
      x = x + dense(self.d_model, "mlp_down")(h)
      return (x, pos, table), (ck, cv)
    if self.decode:
      # Single-token decode over the KV ring buffer. Same submodule
      # names as the forward branch, so trained/initialized variables
      # apply unchanged; op-for-op the forward row's computation, so
      # per-token logits are bit-identical to the full-sequence
      # forward at every prefix length (tests/test_serving.py).
      x, pos = carry
      ck, cv = xs
      b = x.shape[0]
      t_cache = ck.shape[1]
      h = ln("ln1")(x).astype(self.dtype)
      qkv = dense(3 * self.d_model, "qkv", bias=False)(h)
      qkv = qkv.reshape(b, 1, 3, self.n_heads, head_dim)
      # Ring write at pos % T (pure select, no arithmetic on the kept
      # entries -- the bit-identity contract again).
      write = (jnp.arange(t_cache)[None, :] ==
               (pos % t_cache)[:, None])[..., None, None]
      ck = jnp.where(write, qkv[:, :, 1], ck)
      cv = jnp.where(write, qkv[:, :, 2], cv)
      att = sequence_lib.decode_attention(
          qkv[:, :, 0], ck, cv, pos,
          block=min(self.attn_block, t_cache), impl=self.attn_impl,
          exact=self.decode_exact,
          q_block=min(self.attn_q_block, t_cache))
      x = x + dense(self.d_model, "attn_out")(
          att.reshape(b, 1, self.d_model))
      h = ln("ln2")(x).astype(self.dtype)
      h = nn.gelu(dense(self.d_ff, "mlp_up")(h))
      x = x + dense(self.d_model, "mlp_down")(h)
      return (x, pos), (ck, cv)
    # Carry = (hidden states, packed segment ids or None): the segment
    # ids ride the scan carry unchanged so every block's attention sees
    # them without a second scan input (--packed_sequences).
    x, seg = carry
    b, t, _d = x.shape
    h = ln("ln1")(x).astype(self.dtype)
    qkv = dense(3 * self.d_model, "qkv", bias=False)(h)
    qkv = qkv.reshape(b, t, 3, self.n_heads, head_dim)
    blk = min(self.attn_block, t)
    if self.attn_impl == "flash":
      # Matched tilings: the A/B against the tiled path must not
      # confound kernel choice with tile size, so the kernel gets
      # the same block as the scan (long_context_probe.py ditto).
      # Packed runs ride the kernel's native SegmentIds support.
      att = sequence_lib.pallas_flash_attention(
          qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2], causal=True,
          block=blk, segment_ids=seg)
    elif self.attn_impl == "tiled":
      att = sequence_lib.blockwise_attention(
          qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2],
          block_size=blk, causal=True,
          q_block_size=min(self.attn_q_block, t), segment_ids=seg)
    else:
      raise ValueError(
          f"attn_impl must be 'tiled' or 'flash', got "
          f"{self.attn_impl!r}")
    x = x + dense(self.d_model, "attn_out")(
        att.reshape(b, t, self.d_model))
    h = ln("ln2")(x).astype(self.dtype)
    h = nn.gelu(dense(self.d_ff, "mlp_up")(h))
    x = x + dense(self.d_model, "mlp_down")(h)
    # return_kv: the per-position K/V projections ride the scan outputs
    # (stacked (L, B, T, H, Dh) by nn.scan) -- exactly the arrays a
    # decode step would have written at those positions, so a packed
    # prefill builds the same ring-buffer contents the incremental path
    # would (serving/decode.py). None keeps the legacy program.
    if self.return_kv:
      return (x, seg), (qkv[:, :, 1], qkv[:, :, 2])
    return (x, seg), None


class _TransformerLMModule(nn.Module):
  vocab: int = VOCAB
  d_model: int = D_MODEL
  n_layers: int = N_LAYERS
  n_heads: int = N_HEADS
  d_ff: int = D_FF
  attn_block: int = ATTN_BLOCK
  attn_q_block: int = ATTN_Q_BLOCK
  # 'tiled' (XLA two-level scan) or 'flash' (the TPU Pallas kernel) --
  # switchable per run via KF_TRANSFORMER_LM_ATTN for on-chip A/Bs.
  attn_impl: str = "tiled"
  # True: ONE scanned+rematerialized block (params carry a leading
  # layer axis under 'blocks'); False: the unrolled per-layer loop
  # (params under 'block_{i}') -- the equivalence oracle and the
  # program-size A/B.
  scan_layers: bool = True
  # True: return ops.fused_loss.FusedLMHead (hidden, kernel) so the
  # loss reduces chunk-wise without a (B, T, V) tensor; False:
  # materialize logits (the monolithic head the oracle tests pin
  # against).
  fused_head: bool = True
  # Mesh axis for in-backward gradient reduction of the scanned layer
  # stack (--overlap_gradient_reduction, ops/overlap.py): each scan
  # backward iteration then reduces THAT layer's gradient slice inside
  # the loop body, overlapped with the next iteration's backward
  # compute. None = no hooks (the post-hoc reduction path). Only
  # meaningful with scan_layers; requires apply() to run inside a
  # shard_map body where the axis is bound.
  grad_reduce_axis: Any = None
  # Optional 16-bit wire dtype for the hook's collectives
  # (allreduce.compact_wire_dtype); None = the gradient's own dtype.
  grad_reduce_compact: Any = None
  # --shard_params (full FSDP): per-block gather hook
  # (ops/overlap.fsdp_block_gatherer). The 'blocks' stack is STORED as
  # flat per-layer parameter shards ((L, k) locally; ops/sharded.py
  # fsdp_stacked_shards); each nn.scan iteration re-assembles ONE
  # block's full params with a packed all-gather INSIDE the scan body
  # (under nn.remat, so the backward re-gathers during recompute), and
  # the hook's custom_vjp backward reduce-scatters that block's
  # cotangent in the same position -- the full layer stack never
  # materializes. None = plain replicated-param storage. Exclusive
  # with grad_reduce_axis (validation.py rejects --shard_params +
  # --overlap_gradient_reduction upstream).
  fsdp_block_hook: Any = None
  max_len: int = SEQ_LEN
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32
  # Serving (kf_benchmarks_tpu/serving/): decode=True switches
  # __call__ to the single-token KV-ring path -- (tokens (B,),
  # cache_k/cache_v (L, B, T, H, Dh), pos (B,)) -> (logits (B, 1, V),
  # (cache_k', cache_v')); return_kv=True makes the full-sequence
  # forward additionally return the stacked per-layer K/V projections
  # (the packed-prefill cache source). Both off = the exact legacy
  # program (golden contracts unchanged). decode_exact selects the
  # bit-identity oracle attention schedule over the ~T x cheaper 1-row
  # production one (sequence.decode_attention).
  decode: bool = False
  return_kv: bool = False
  decode_exact: bool = False
  # Paged KV decode (serving/decode.py paged mode): >0 makes the decode
  # path take (L, P, page, H, Dh) page POOLS plus a (B, pages_per_slot)
  # page table instead of the dense per-slot ring slab (the _Block
  # field of the same name). 0 = dense ring; the forward/training
  # program never sees it.
  kv_page_size: int = 0

  @nn.compact
  def __call__(self, tokens, cache_k=None, cache_v=None, pos=None,
               page_table=None):
    if self.decode:
      return self._decode_call(tokens, cache_k, cache_v, pos,
                               page_table)
    tokens = tokens.astype(jnp.int32)
    seg = positions = None
    if tokens.ndim == 3:
      # Packed input (--packed_sequences): the (B, 3, T) int32 stack
      # [tokens, segment_ids, positions] from data/packing.py. Shape
      # is the mode switch, so the module needs no config flag and
      # unpacked callers keep the exact legacy program.
      tokens, seg, positions = (tokens[:, 0], tokens[:, 1],
                                tokens[:, 2])
    b, t = tokens.shape
    block_kwargs = dict(
        d_model=self.d_model, n_heads=self.n_heads, d_ff=self.d_ff,
        attn_block=self.attn_block, attn_q_block=self.attn_q_block,
        attn_impl=self.attn_impl, dtype=self.dtype,
        param_dtype=self.param_dtype, return_kv=self.return_kv)

    x = nn.Embed(self.vocab, self.d_model, name="embed",
                 dtype=self.dtype, param_dtype=self.param_dtype)(tokens)
    pos = self.param(
        "pos_embedding",
        nn.initializers.normal(0.02, self.param_dtype),
        (self.max_len, self.d_model))
    if positions is None:
      x = x + pos[:t].astype(self.dtype)
    else:
      # Per-document positions (restart at 0 per segment): a packed
      # document reads the same position rows it would alone.
      x = x + jnp.take(pos, positions, axis=0).astype(self.dtype)

    if self.scan_layers:
      # One block body in the compiled program regardless of depth;
      # jax.checkpoint per block (nn.remat) keeps only the block
      # boundaries as backward residuals. prevent_cse=False is the
      # scan-safe setting (the scan barrier already blocks the CSE
      # that prevent_cse guards against; True pessimizes TPU code).
      block_cls = _Block
      if self.fsdp_block_hook is not None:
        # FSDP storage -> full block params, one packed all-gather per
        # scan iteration (ops/overlap.py gather_params). Init stays
        # full-shape and collective-free: the hook passes the empty
        # pre-creation store through, so module.init creates FULL
        # params under plain jit and the train step's init_state
        # re-stacks them into the shard layout host-side.
        block_cls = nn.map_variables(
            _Block, "params", trans_in_fn=self.fsdp_block_hook,
            init=True)
      elif self.grad_reduce_axis is not None:
        # In-backward reduction hook (ops/overlap.py): the block's
        # per-layer param slice passes through an identity-with-
        # custom_vjp whose backward pmeans the slice's cotangent, so
        # the collective lands INSIDE the backward scan's loop body
        # (pinned at the HLO level by tests/test_overlap_reduction.py).
        # The forward transform is the identity, so init (init=True)
        # and eval apply are unaffected.
        from kf_benchmarks_tpu.ops import overlap as overlap_lib
        block_cls = nn.map_variables(
            _Block, "params",
            trans_in_fn=overlap_lib.scan_block_hook(
                self.grad_reduce_axis,
                compact_dtype=self.grad_reduce_compact),
            init=True)
      blocks = nn.scan(
          nn.remat(block_cls, prevent_cse=False),
          variable_axes={"params": 0},
          split_rngs={"params": True},
          length=self.n_layers)(name="blocks", **block_kwargs)
      (x, _), kv = blocks((x, seg), None)
    else:
      kv_rows = []
      for i in range(self.n_layers):
        (x, _), kv_i = _Block(name=f"block_{i}", **block_kwargs)(
            (x, seg), None)
        kv_rows.append(kv_i)
      # Stack the per-layer K/V rows like nn.scan would, so the two
      # layer paths hand serving the same (L, B, T, H, Dh) layout.
      kv = (jnp.stack([r[0] for r in kv_rows]),
            jnp.stack([r[1] for r in kv_rows])) if self.return_kv \
          else None

    x = nn.LayerNorm(name="ln_f", dtype=jnp.float32,
                     param_dtype=self.param_dtype)(x)
    # The head computes in the model dtype: at 32k vocab an f32 logits
    # tensor is the HBM peak (measured OOM at bs=8 on 16 GB, PERF.md);
    # the loss upcasts per sequence chunk instead.
    w_head = self.param("lm_head", nn.initializers.lecun_normal(),
                        (self.d_model, self.vocab), self.param_dtype)
    aux = None
    if seg is not None:
      # Packed runs hand the per-token loss weights to the loss and
      # accuracy functions through the aux slot (the ONE derivation,
      # data/packing.py): 0 at padding and document-final slots.
      from kf_benchmarks_tpu.data import packing as packing_lib
      aux = packing_lib.token_weights_from_segments(seg)
    if self.fused_head:
      # No logits here at ALL: the head matmul itself is deferred into
      # the chunked loss/accuracy reductions (ops/fused_loss.py).
      out = fused_loss_lib.FusedLMHead(
          hidden=x.astype(self.dtype), kernel=w_head)
    else:
      out = x.astype(self.dtype) @ w_head.astype(self.dtype)
    if self.return_kv:
      return out, aux, kv
    return out, aux

  def _decode_call(self, tokens, cache_k, cache_v, pos, page_table=None):
    """The single-token KV-ring decode step (serving/decode.py).

    ``tokens`` (B,) int32 is each slot's CURRENT token at absolute
    position ``pos`` (B,); its K/V are written into the ring at
    ``pos % T`` and the returned (B, 1, V) logits predict position
    ``pos + 1``. Ring semantics: within the first ``max_len`` tokens
    the cache index IS the absolute position (and decode is
    bit-identical to the full-sequence forward); past it the buffer
    wraps and attention covers the trailing ``max_len``-token window.
    Always the dense head -- a (B, 1, V) logits row is microscopic
    next to the fused head's reason for existing.

    With ``kv_page_size`` set, ``cache_k``/``cache_v`` are the shared
    (L, P, page, H, Dh) page pools and ``page_table`` the per-slot
    (B, pages_per_slot) pool-row map; the table rides the scan carry
    (shared by every layer) while the pools stay the scanned
    input/output, so the layer structure is the dense branch's.
    """
    tok = tokens.astype(jnp.int32).reshape(-1, 1)
    b = tok.shape[0]
    block_kwargs = dict(
        d_model=self.d_model, n_heads=self.n_heads, d_ff=self.d_ff,
        attn_block=self.attn_block, attn_q_block=self.attn_q_block,
        attn_impl=self.attn_impl, dtype=self.dtype,
        param_dtype=self.param_dtype, decode=True,
        decode_exact=self.decode_exact,
        kv_page_size=self.kv_page_size)
    x = nn.Embed(self.vocab, self.d_model, name="embed",
                 dtype=self.dtype, param_dtype=self.param_dtype)(tok)
    pos_emb = self.param(
        "pos_embedding",
        nn.initializers.normal(0.02, self.param_dtype),
        (self.max_len, self.d_model))
    # Per-slot position row (ring-wrapped past max_len): the same table
    # row the full forward adds at that position.
    x = x + jnp.take(pos_emb, pos % self.max_len,
                     axis=0)[:, None, :].astype(self.dtype)
    if self.kv_page_size:
      carry_in = (x, pos, page_table.astype(jnp.int32))
    else:
      carry_in = (x, pos)
    if self.scan_layers:
      blocks = nn.scan(
          _Block,
          variable_axes={"params": 0},
          split_rngs={"params": True},
          length=self.n_layers)(name="blocks", **block_kwargs)
      carry_out, (ck, cv) = blocks(carry_in, (cache_k, cache_v))
      x = carry_out[0]
    else:
      cks, cvs = [], []
      carry = carry_in
      for i in range(self.n_layers):
        carry, (ck_i, cv_i) = _Block(name=f"block_{i}", **block_kwargs)(
            carry, (cache_k[i], cache_v[i]))
        cks.append(ck_i)
        cvs.append(cv_i)
      x = carry[0]
      ck, cv = jnp.stack(cks), jnp.stack(cvs)
    x = nn.LayerNorm(name="ln_f", dtype=jnp.float32,
                     param_dtype=self.param_dtype)(x)
    w_head = self.param("lm_head", nn.initializers.lecun_normal(),
                        (self.d_model, self.vocab), self.param_dtype)
    logits = x.astype(self.dtype) @ w_head.astype(self.dtype)
    return logits, (ck, cv)


class TransformerLMModel(model_lib.Model):
  """Decoder-only LM over synthetic token streams (no reference
  counterpart; the zoo's long-context member)."""

  def __init__(self, params=None):
    super().__init__("transformer_lm", batch_size=8, learning_rate=0.05,
                     fp16_loss_scale=128, params=params)
    # --packed_sequences: inputs become the (B, 3, T) packed stack and
    # losses/metrics weight by real-token count (data/packing.py).
    self.packed = bool(getattr(params, "packed_sequences", False)
                       ) if params is not None else False
    if self.packed:
      from kf_benchmarks_tpu.data import packing as packing_lib
      # The train step's token-weighted metric combine reads each
      # replica's real-label weights from the packed input stack
      # (images[:, 1] = segment ids) -- the same derivation the
      # module's aux weights use, so loss and metrics cannot drift.
      self.token_weight_fn = (
          lambda images: packing_lib.token_weights_from_segments(
              images[:, 1]))

  def make_module(self, nclass, phase_train, data_format="NHWC",
                  dtype=jnp.float32, param_dtype=jnp.float32):
    del nclass, data_format
    import os
    impl = os.environ.get("KF_TRANSFORMER_LM_ATTN", "tiled")
    if impl not in ("tiled", "flash"):
      raise ValueError(
          f"KF_TRANSFORMER_LM_ATTN must be 'tiled' or 'flash', got "
          f"{impl!r}")
    head = os.environ.get("KF_TRANSFORMER_LM_HEAD", "fused")
    if head not in ("fused", "dense"):
      raise ValueError(
          f"KF_TRANSFORMER_LM_HEAD must be 'fused' or 'dense', got "
          f"{head!r}")
    layers = os.environ.get("KF_TRANSFORMER_LM_LAYERS", "scan")
    if layers not in ("scan", "loop"):
      raise ValueError(
          f"KF_TRANSFORMER_LM_LAYERS must be 'scan' or 'loop', got "
          f"{layers!r}")
    # --attn_block (validated against SEQ_LEN in validation.py): one
    # value drives BOTH tilings -- the K/V block and the matched
    # q-block -- so an autotuned size never confounds the two-level
    # schedule with mismatched tiles (the matched-tilings rule the
    # flash/tiled A/B already follows). None = the module defaults.
    attn_block = int(getattr(self.params, "attn_block", None) or 0) \
        if self.params is not None else 0
    # Scan-over-layers params carry a leading depth axis under 'blocks'
    # (PR 2): observability.SummaryWriter unstacks histogram keys per
    # layer via this attribute (tests/test_observability.py).
    self.scanned_param_prefixes = ("blocks",) if layers == "scan" else ()
    # --overlap_gradient_reduction: hook the scanned layer stack so
    # each backward scan iteration reduces its OWN layer's gradient
    # slice inside the loop body (ops/overlap.py scan_block_hook). The
    # training module only (eval has no backward); disengaged under
    # --num_grad_accum, where reduction stays post-hoc on the
    # accumulated tree (train_step.py). in_backward_reduced_prefixes
    # tells the step-level bucket planner these leaves are covered.
    grad_reduce_axis = None
    grad_reduce_compact = None
    p = self.params
    if (phase_train and layers == "scan" and p is not None
        and getattr(p, "overlap_gradient_reduction", False)
        and (getattr(p, "num_grad_accum", 1) or 1) == 1):
      from kf_benchmarks_tpu.ops import allreduce
      from kf_benchmarks_tpu.parallel.mesh import REPLICA_AXIS
      grad_reduce_axis = REPLICA_AXIS
      grad_reduce_compact = allreduce.compact_wire_dtype(p)
      self.in_backward_reduced_prefixes = ("blocks",)
    # --shard_params (full FSDP): the scanned 'blocks' stack stores as
    # per-layer parameter shards and each scan iteration gathers ONE
    # block inside the loop body (ops/overlap.fsdp_block_gatherer).
    # fsdp_gathered_prefixes tells the step-level bucket gather
    # (train_step.py) these leaves are module-gathered. Training module
    # only: eval applies the PLAIN module to the step-gathered full
    # tree. The loop fallback needs no hook -- its per-layer 'block_i'
    # top keys are exactly the builder-layer buckets the step gathers.
    fsdp_block_hook = None
    if (phase_train and layers == "scan" and p is not None
        and getattr(p, "shard_params", False)):
      from kf_benchmarks_tpu.ops import overlap as overlap_lib
      from kf_benchmarks_tpu.parallel.mesh import BATCH_AXIS, MODEL_AXIS
      plain = _TransformerLMModule(dtype=dtype, param_dtype=param_dtype,
                                   attn_impl=impl,
                                   fused_head=head == "fused",
                                   scan_layers=True)
      sample = jnp.zeros(tuple(self.get_input_shapes("train")[0]),
                         jnp.int32)
      # Abstract init (nothing executes): one block's full shapes =
      # the stacked 'blocks' leaves with the leading layer axis
      # stripped -- the gather spec the hook re-assembles against.
      variables = jax.eval_shape(
          lambda: plain.init({"params": jax.random.PRNGKey(0),
                              "dropout": jax.random.PRNGKey(0)}, sample))
      block_template = jax.tree.map(
          lambda s: jax.ShapeDtypeStruct(tuple(s.shape)[1:], s.dtype),
          variables["params"]["blocks"])
      # --partitioner=gspmd traces the step under double vmap, which
      # has no tuple-axis all_gather batching rule (jax 0.4.x): the
      # hook's forward gather decomposes per axis there (element-
      # identical; ops/sharded.combined_all_gather).
      fsdp_block_hook = overlap_lib.fsdp_block_gatherer(
          block_template, BATCH_AXIS, MODEL_AXIS,
          nested=getattr(p, "partitioner", None) == "gspmd")
      self.fsdp_gathered_prefixes = ("blocks",)
    tiling = (dict(attn_block=attn_block, attn_q_block=attn_block)
              if attn_block else {})
    return _TransformerLMModule(dtype=dtype, param_dtype=param_dtype,
                                attn_impl=impl,
                                fused_head=head == "fused",
                                scan_layers=layers == "scan",
                                grad_reduce_axis=grad_reduce_axis,
                                grad_reduce_compact=grad_reduce_compact,
                                fsdp_block_hook=fsdp_block_hook,
                                **tiling)

  def get_input_shapes(self, subset):
    n = self.get_batch_size()
    if self.packed:
      # [tokens, segment_ids, positions] stacked (data/packing.py).
      return [[n, 3, SEQ_LEN], [n, SEQ_LEN]]
    return [[n, SEQ_LEN], [n, SEQ_LEN]]

  def get_input_data_types(self, subset):
    return [jnp.int32, jnp.int32]

  def get_synthetic_inputs(self, rng, nclass):
    n = self.get_batch_size()
    if self.packed:
      # One deterministic packed batch (direct callers / AOT; the
      # benchmark streams fresh batches through the DeviceFeeder
      # instead, benchmark.py _input_iterator).
      from kf_benchmarks_tpu.data import packing as packing_lib
      stream = packing_lib.PackedBatchStream(
          SEQ_LEN, n, VOCAB, seed=int(jax.random.randint(
              rng, (), 0, 2**31 - 1)))
      images, labels = next(stream)
      return jnp.asarray(images), jnp.asarray(labels)
    tokens = jax.random.randint(rng, (n, SEQ_LEN), 0, VOCAB, jnp.int32)
    # Next-token labels: the shifted stream, so the synthetic objective
    # is the real LM objective (learnable, not pure noise).
    labels = jnp.roll(tokens, -1, axis=1)
    return tokens, labels

  # Sequence-chunk size for the loss: the f32 softmax temps live one
  # chunk at a time ((B, 256, 32768) f32 = 268 MB at bs 8) instead of
  # the whole (B, T, V) tensor, and jax.checkpoint makes the backward
  # recompute per chunk rather than keep every chunk's softmax alive.
  LOSS_CHUNK = 256

  def loss_function(self, build_network_result, labels):
    # aux carries the packed per-token loss weights (the module derives
    # them from the segment ids); None on unpacked runs.
    out, weights = build_network_result.logits
    labels = labels.astype(jnp.int32)
    if isinstance(out, fused_loss_lib.FusedLMHead):
      # Fused head: loss straight from (hidden, kernel); no logits
      # tensor exists anywhere in the step (ops/fused_loss.py).
      return fused_loss_lib.fused_softmax_xent(
          out.hidden, out.kernel, labels, chunk_size=self.LOSS_CHUNK,
          weights=weights)
    # Dense-head fallback: logits are materialized; chunk the softmax
    # reduction only (the round-6 bounded-memory path).
    logits = out
    b, t, v = logits.shape
    chunk = fused_loss_lib.chunk_of(t, self.LOSS_CHUNK)
    lc = logits.reshape(b, t // chunk, chunk, v).swapaxes(0, 1)
    yc = labels.reshape(b, t // chunk, chunk).swapaxes(0, 1)
    wc = None if weights is None else weights.astype(
        jnp.float32).reshape(b, t // chunk, chunk).swapaxes(0, 1)

    @jax.checkpoint
    def body(carry, xs):
      lg, yy, ww = xs
      logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
      ll = jnp.take_along_axis(logp, yy[..., None], axis=-1)
      if ww is not None:
        ll = ll * ww[..., None]
      return carry + jnp.sum(ll), None

    (zero,) = sequence_lib.vary_like(logits,
                                     (jnp.zeros((), jnp.float32),))
    total, _ = jax.lax.scan(body, zero, (lc, yc, wc))
    if weights is None:
      return -total / (b * t)
    return -total / jnp.maximum(
        jnp.sum(weights.astype(jnp.float32)), 1.0)

  def accuracy_function(self, build_network_result, labels):
    out, weights = build_network_result.logits
    labels = labels.astype(jnp.int32)
    if isinstance(out, fused_loss_lib.FusedLMHead):
      return fused_loss_lib.fused_top_k_accuracy(
          out.hidden, out.kernel, labels, chunk_size=self.LOSS_CHUNK,
          weights=weights)
    logits = out
    # argmax/top_k reduce away the vocab axis chunk-free (no f32
    # upcast of the full logits tensor is ever materialised).
    hit1 = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
    hit5 = jnp.any(jax.lax.top_k(logits, 5)[1] == labels[..., None],
                   axis=-1).astype(jnp.float32)
    if weights is None:
      return {"top_1_accuracy": jnp.mean(hit1),
              "top_5_accuracy": jnp.mean(hit5)}
    w = weights.astype(jnp.float32)
    denom = jnp.maximum(jnp.sum(w), 1.0)
    return {"top_1_accuracy": jnp.sum(hit1 * w) / denom,
            "top_5_accuracy": jnp.sum(hit5 * w) / denom}


def create_transformer_lm_model(params=None):
  return TransformerLMModel(params=params)
