"""AlexNet model configurations (ref: models/alexnet_model.py).

Krizhevsky, Sutskever, Hinton, "ImageNet Classification with Deep
Convolutional Neural Networks" (NeurIPS 2012); the cifar variant follows
the TF cifar10 tutorial model.
"""

import jax.numpy as jnp

from kf_benchmarks_tpu.models import model


class AlexnetModel(model.CNNModel):
  """(ref: models/alexnet_model.py:27-49)"""

  def __init__(self, params=None):
    # 224 + 3: VALID convs require the images padded by 3 in H and W.
    super().__init__("alexnet", 224 + 3, 512, 0.005, params=params)

  def add_inference(self, cnn):
    cnn.conv(64, 11, 11, 4, 4, "VALID")
    cnn.mpool(3, 3, 2, 2)
    cnn.conv(192, 5, 5)
    cnn.mpool(3, 3, 2, 2)
    cnn.conv(384, 3, 3)
    cnn.conv(384, 3, 3)
    cnn.conv(256, 3, 3)
    cnn.mpool(3, 3, 2, 2)
    cnn.reshape([-1, 256 * 6 * 6])
    cnn.affine(4096)
    cnn.dropout()
    cnn.affine(4096)
    cnn.dropout()


class AlexnetCifar10Model(model.CNNModel):
  """Cifar-sized AlexNet from the TF tutorial (ref: models/alexnet_model.py:52-92)."""

  def __init__(self, params=None):
    super().__init__("alexnet", 32, 128, 0.1, params=params)

  def add_inference(self, cnn):
    cnn.conv(64, 5, 5, 1, 1, "SAME", stddev=5e-2)
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    cnn.lrn(depth_radius=4, bias=1.0, alpha=0.001 / 9.0, beta=0.75)
    cnn.conv(64, 5, 5, 1, 1, "SAME", bias=0.1, stddev=5e-2)
    cnn.lrn(depth_radius=4, bias=1.0, alpha=0.001 / 9.0, beta=0.75)
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    shape = cnn.top_layer.shape
    flat_dim = shape[1] * shape[2] * shape[3]
    cnn.reshape([-1, flat_dim])
    cnn.affine(384, stddev=0.04, bias=0.1)
    cnn.affine(192, stddev=0.04, bias=0.1)

  def get_learning_rate(self, global_step, batch_size):
    """Staircase exponential decay, 0.1x every 100 epochs
    (ref: models/alexnet_model.py:80-92)."""
    num_examples_per_epoch = 50000
    num_epochs_per_decay = 100
    decay_steps = int(num_epochs_per_decay * num_examples_per_epoch
                      / batch_size)
    num_decays = jnp.floor(jnp.asarray(global_step, jnp.float32)
                           / decay_steps)
    return self.learning_rate * jnp.power(0.1, num_decays)
