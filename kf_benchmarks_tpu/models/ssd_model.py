"""SSD300 with modified ResNet-34 backbone (COCO detection).

TPU-native re-design of the reference SSD model (ref:
scripts/tf_cnn_benchmarks/models/ssd_model.py:47-552): backbone per
:96-136 (ResNet-34 with group 3 kept at stride 1 and group 4 removed),
extra feature layers and per-level heads per :138-221, multibox loss
with hard negative mining per :299-384 (double-argsort rank trick kept
-- it is jittable as-is), MLPerf LR schedule per :223-255, synthetic
inputs per :541-552.

Detection targets ride the ``labels`` slot of the training step as a
(encoded_boxes, classes, num_matched) tuple; the step treats labels as a
pytree, so nothing else changes. Head outputs are flattened
location-major to agree with DefaultBoxes order (see ssd_dataloader.py's
ordering note).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import flax.linen as nn

from kf_benchmarks_tpu.models import model as model_lib
from kf_benchmarks_tpu.models import resnet_model
from kf_benchmarks_tpu.models import ssd_constants
from kf_benchmarks_tpu.models import ssd_dataloader
from kf_benchmarks_tpu.models.builder import ConvNetBuilder

BACKBONE_MODEL_SCOPE_NAME = "resnet34_backbone"


class _SSDModule(nn.Module):
  """Backbone + extra layers + multibox heads, one compact module."""

  label_num: int
  phase_train: bool
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, images):
    cnn = ConvNetBuilder(
        input_layer=images, phase_train=self.phase_train,
        data_format="NHWC", dtype=self.dtype,
        param_dtype=self.param_dtype, use_batch_norm=True,
        batch_norm_config={"decay": ssd_constants.BATCH_NORM_DECAY,
                           "epsilon": ssd_constants.BATCH_NORM_EPSILON,
                           "scale": True})

    # ResNet-34 backbone, SSD-modified (ref: ssd_model.py:96-136):
    # group 3 keeps stride 1 so the 38x38 map survives; group 4 removed.
    cnn.conv(64, 7, 7, 2, 2, mode="SAME_RESNET", use_batch_norm=True)
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    for _ in range(3):
      resnet_model.residual_block(cnn, 64, 1, "v1")
    for i in range(4):
      resnet_model.residual_block(cnn, 128, 2 if i == 0 else 1, "v1")
    for i in range(6):
      resnet_model.residual_block(cnn, 256, 1, "v1")

    def ssd_layer(depth, k, stride, mode):
      return cnn.conv(depth, k, k, stride, stride, mode=mode,
                      use_batch_norm=False)

    activations = [cnn.top_layer]  # 38x38x256
    ssd_layer(256, 1, 1, "VALID")
    activations.append(ssd_layer(512, 3, 2, "SAME"))   # 19x19
    ssd_layer(256, 1, 1, "VALID")
    activations.append(ssd_layer(512, 3, 2, "SAME"))   # 10x10
    ssd_layer(128, 1, 1, "VALID")
    activations.append(ssd_layer(256, 3, 2, "SAME"))   # 5x5
    ssd_layer(128, 1, 1, "VALID")
    activations.append(ssd_layer(256, 3, 1, "VALID"))  # 3x3
    ssd_layer(128, 1, 1, "VALID")
    activations.append(ssd_layer(256, 3, 1, "VALID"))  # 1x1

    locs, confs = [], []
    batch = images.shape[0]
    for nd, act in zip(ssd_constants.NUM_DEFAULTS, activations):
      # Location-major flatten: [b, s, s, nd*4] -> [b, s*s*nd, 4],
      # matching DefaultBoxes (i, j, default) order.
      l = cnn.conv(nd * 4, 3, 3, 1, 1, input_layer=act, activation=None,
                   use_batch_norm=False)
      locs.append(l.reshape(batch, -1, 4))
      c = cnn.conv(nd * self.label_num, 3, 3, 1, 1, input_layer=act,
                   activation=None, use_batch_norm=False)
      confs.append(c.reshape(batch, -1, self.label_num))
    locs = jnp.concatenate(locs, axis=1)
    confs = jnp.concatenate(confs, axis=1)
    # [b, NUM_SSD_BOXES, 4 + label_num], as the reference packs them
    # (ref: ssd_model.py:213-218).
    logits = jnp.concatenate([locs, confs], axis=2).astype(jnp.float32)
    return logits, None


class SSD300Model(model_lib.CNNModel):
  """SSD300 (ref: models/ssd_model.py:47-552)."""

  def __init__(self, label_num=ssd_constants.NUM_CLASSES, batch_size=32,
               learning_rate=1e-3, backbone="resnet34", params=None):
    super().__init__("ssd300", 300, batch_size, learning_rate,
                     params=params)
    if backbone != "resnet34":
      raise ValueError(f"Unsupported backbone {backbone!r}")
    self.label_num = label_num
    # Checkpoint-poll eval state (ref :76-86).
    self.eval_global_step = 0
    self.predictions = {}

  def skip_final_affine_layer(self):
    return True

  def make_module(self, nclass, phase_train, data_format="NHWC",
                  dtype=jnp.float32, param_dtype=jnp.float32):
    del nclass, data_format  # label_num is fixed by COCO; NHWC throughout
    return _SSDModule(label_num=self.label_num, phase_train=phase_train,
                      dtype=dtype, param_dtype=param_dtype)

  # -- inputs ---------------------------------------------------------------

  def get_input_shapes(self, subset):
    """images + (encoded boxes, classes, num_matched) (ref :401-428)."""
    n = self.get_batch_size()
    return [[n, self.image_size, self.image_size, self.depth],
            [n, ssd_constants.NUM_SSD_BOXES, 4],
            [n, ssd_constants.NUM_SSD_BOXES],
            [n]]

  def get_input_data_types(self, subset):
    return [jnp.float32, jnp.float32, jnp.int32, jnp.float32]

  def get_synthetic_inputs(self, rng, nclass):
    """(ref :541-552) -- random images; a plausible random target set."""
    shapes = self.get_input_shapes("train")
    r_img, r_cls, r_n = jax.random.split(rng, 3)
    images = jax.random.uniform(r_img, shapes[0], jnp.float32)
    boxes = jnp.zeros(shapes[1], jnp.float32)
    classes = jnp.where(
        jax.random.uniform(r_cls, shapes[2]) > 0.99,
        jax.random.randint(r_n, shapes[2], 1, self.label_num), 0
    ).astype(jnp.int32)
    num_matched = jnp.maximum(
        jnp.sum((classes > 0).astype(jnp.float32), axis=1), 1.0)
    return images, (boxes, classes, num_matched)

  # -- losses (ref :299-384) ------------------------------------------------

  def loss_function(self, build_network_result, labels):
    logits, _ = build_network_result.logits
    pred_loc = logits[..., :4]
    pred_label = logits[..., 4:]
    gt_loc, gt_label, num_matched = labels
    gt_label = gt_label.astype(jnp.int32)
    box_loss = self._localization_loss(pred_loc, gt_loc, gt_label,
                                       num_matched)
    class_loss = self._classification_loss(pred_label, gt_label,
                                           num_matched)
    return box_loss + class_loss

  def _localization_loss(self, pred_loc, gt_loc, gt_label, num_matched):
    """Smooth-L1 over positive anchors (ref :320-347)."""
    mask = (gt_label > 0).astype(jnp.float32)
    diff = pred_loc - gt_loc
    abs_diff = jnp.abs(diff)
    huber = jnp.where(abs_diff < 1.0, 0.5 * diff * diff, abs_diff - 0.5)
    per_anchor = jnp.sum(huber, axis=2) * mask
    per_image = jnp.sum(per_anchor, axis=1)
    return jnp.mean(per_image / num_matched)

  def _classification_loss(self, pred_label, gt_label, num_matched):
    """Softmax xent with 3:1 hard negative mining (ref :348-384).

    The reference's double-argsort rank trick is kept: rank each
    negative anchor by its loss, keep the top 3*num_matched.
    """
    logp = jax.nn.log_softmax(pred_label)
    xent = -jnp.take_along_axis(logp, gt_label[..., None],
                                axis=2).squeeze(-1)
    mask = (gt_label > 0).astype(jnp.float32)
    neg_xent = xent * (1.0 - mask)
    order = jnp.argsort(-neg_xent, axis=1)
    rank = jnp.argsort(order, axis=1)
    num_negs = jnp.minimum(num_matched * ssd_constants.NEGS_PER_POSITIVE,
                           ssd_constants.NUM_SSD_BOXES)
    top_k_neg_mask = (rank < num_negs[:, None].astype(rank.dtype)) \
        .astype(jnp.float32)
    per_image = jnp.sum(xent * (mask + top_k_neg_mask), axis=1)
    return jnp.mean(per_image / num_matched)

  # -- lr schedule (ref :223-255) -------------------------------------------

  def get_scaled_base_learning_rate(self, batch_size):
    return self.learning_rate * batch_size / 32.0

  def get_learning_rate(self, global_step, batch_size):
    rescaled = self.get_scaled_base_learning_rate(batch_size)
    step = jnp.asarray(global_step, jnp.int32)
    lr = jnp.asarray(ssd_constants.LEARNING_RATE_SCHEDULE[0][1], jnp.float32)
    for boundary, value in ssd_constants.LEARNING_RATE_SCHEDULE[1:]:
      lr = jnp.where(step >= boundary, jnp.asarray(value, jnp.float32), lr)
    return lr * (rescaled / ssd_constants.LEARNING_RATE_SCHEDULE[0][1])

  # -- eval -----------------------------------------------------------------

  def accuracy_function(self, build_network_result, labels):
    """Decode predictions for COCO accumulation (ref :430-479). Detection
    has no top-k accuracy; the mAP is computed in postprocess over the
    accumulated predictions."""
    logits, _ = build_network_result.logits
    pred_loc = logits[..., :4]
    pred_scores = jax.nn.softmax(logits[..., 4:], axis=-1)
    anchors = ssd_dataloader._default_boxes_singleton()("xywh")
    decoded = ssd_dataloader.decode_boxes(pred_loc, anchors)
    # Benchmark-loop compatibility: detection reports a proxy "accuracy"
    # of mean max-class confidence so the shared eval loop has scalars on
    # the synthetic path. Real-COCO eval (per-image accumulation + mAP)
    # runs through evaluate_real_data below instead.
    top_conf = jnp.max(pred_scores[..., 1:], axis=-1)
    return {"top_1_accuracy": jnp.mean(top_conf),
            "top_5_accuracy": jnp.mean(top_conf),
            "pred_boxes": decoded,
            "pred_scores": pred_scores}

  def postprocess(self, results):
    """COCO mAP over accumulated predictions when pycocotools + the
    annotation file are available (ref :481-539 async COCO eval)."""
    try:
      from kf_benchmarks_tpu import coco_metric
    except ImportError:
      return results
    return coco_metric.maybe_compute_map(results, self.params)

  def evaluate_real_data(self, variables, params, dataset):
    """Real-COCO validation eval: forward the eval module over the
    validation stream, decode + accumulate per-image predictions, then
    compute mAP (ref: _eval_once accuracy accumulation + postprocess,
    ssd_model.py:430-539; benchmark.py dispatches here because detection
    eval is per-image accumulation, not the scalar top-k loop).

    ``variables`` is the unstacked {'params': ..., 'batch_stats': ...}
    flax variables dict. Returns the postprocess()ed results dict.
    """
    import numpy as np
    from kf_benchmarks_tpu.data import preprocessing as pre_lib
    from kf_benchmarks_tpu.parallel import mesh as mesh_lib
    self.params = params  # postprocess reads data_dir for annotations
    module = self.make_module(self.label_num, phase_train=False)
    # Batch sharded over THIS process's devices: detection eval is
    # embarrassingly batch-parallel within a process; under multi-process
    # SPMD each process evaluates the full validation set redundantly on
    # its local mesh (identical results everywhere, no cross-process
    # arrays to gather; the chief's report is the one consumed).
    num_devices = max(getattr(params, "num_devices", 1) or 1, 1)
    batch = self.get_batch_size() * num_devices
    local = [d for d in jax.local_devices()
             if params.device != "cpu" or d.platform == "cpu"]
    mesh = mesh_lib.build_mesh(devices=local[:num_devices])
    batch_sharding = mesh_lib.batch_sharding(mesh)
    variables = jax.device_put(variables,
                               mesh_lib.replicated_sharding(mesh))
    pre = pre_lib.COCOPreprocessor(
        batch_size=batch,
        output_shape=(self.image_size, self.image_size, self.depth),
        train=False, distortions=False, resize_method="bilinear",
        seed=params.tf_random_seed or 301, shift_ratio=0.0,
        num_threads=params.datasets_num_private_threads or 4)
    apply_fn = jax.jit(lambda v, x: module.apply(v, x))
    anchors = ssd_dataloader._default_boxes_singleton()("xywh")
    predictions = []
    num_batches = 0
    for images, (_, _, source_ids, raw_shapes) in pre.minibatches(
        dataset, "validation"):
      x = jnp.asarray(images)
      if x.shape[0] % num_devices == 0:
        x = jax.device_put(x, batch_sharding)
      logits, _ = apply_fn(variables, x)
      logits = np.asarray(logits)
      decoded = np.asarray(
          ssd_dataloader.decode_boxes(jnp.asarray(logits[..., :4]),
                                      anchors))
      scores = np.asarray(jax.nn.softmax(jnp.asarray(logits[..., 4:]),
                                         axis=-1))
      for b in range(len(images)):
        predictions.append({
            "source_id": int(source_ids[b]),
            "pred_boxes": decoded[b],
            "pred_scores": scores[b],
            "raw_shape": np.asarray(raw_shapes[b]),
        })
      num_batches += 1
      if params.num_eval_batches and num_batches >= params.num_eval_batches:
        break
    results = {"predictions": predictions,
               "num_eval_images": len(predictions)}
    return self.postprocess(results)


def create_ssd300_model(params=None):
  return SSD300Model(params=params)
