"""Abstract model API.

TPU-native re-design of the reference model base classes (ref:
scripts/tf_cnn_benchmarks/models/model.py:31-312). The TF graph-mode
``build_network`` becomes a flax.linen module factory: the benchmark
runtime owns init/apply and parameter state, models only describe
architecture + loss/accuracy/LR-policy.

Note: the reference fork commented out the final affine layer
(models/model.py:268-272, a debugging leftover); this rebuild restores it
(``skip_final_affine_layer`` defaults False like the TF1 original,
models/model_legacy.py).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from kf_benchmarks_tpu.models import builder as builder_lib


class BuildNetworkResult(NamedTuple):
  """Result of a forward pass (ref: models/model.py:23-28)."""
  logits: Any
  extra_info: Any = None


class Model:
  """Base model: name, shapes, losses, metrics (ref: models/model.py:31)."""

  def __init__(self, name: str, batch_size: int, learning_rate: float,
               fp16_loss_scale: float = 128.0, params=None):
    self.name = name
    self.batch_size = batch_size
    self.default_batch_size = batch_size
    self.learning_rate = learning_rate
    # bfloat16 needs no loss scaling; the reference's fp16 default is kept
    # for fp16_vars mode (ref: models/model.py:55-60).
    self.fp16_loss_scale = fp16_loss_scale
    self.params = params
    # Top-level param-tree keys whose gradients the model's module
    # reduces IN-BACKWARD itself under --overlap_gradient_reduction
    # (e.g. transformer_lm's scanned 'blocks' hook per layer inside the
    # nn.scan); make_module sets it when it builds such hooks, and
    # train_step's bucket planner excludes those leaves so each
    # gradient reduces exactly once (ops/overlap.py).
    self.in_backward_reduced_prefixes = ()

  def get_name(self) -> str:
    return self.name

  def get_batch_size(self) -> int:
    return self.batch_size

  def set_batch_size(self, batch_size: int) -> None:
    self.batch_size = batch_size

  def get_default_batch_size(self) -> int:
    return self.default_batch_size

  def get_fp16_loss_scale(self) -> float:
    return self.fp16_loss_scale

  def get_learning_rate(self, global_step, batch_size):
    """Model-default LR schedule; scalar or step-indexed (ref :70-75)."""
    del global_step, batch_size
    return self.learning_rate

  def get_input_shapes(self, subset: str) -> Sequence[Sequence[int]]:
    raise NotImplementedError

  def get_input_data_types(self, subset: str) -> Sequence[Any]:
    raise NotImplementedError

  def get_synthetic_inputs(self, rng, nclass: int):
    raise NotImplementedError

  def make_module(self, nclass: int, phase_train: bool, data_format: str,
                  dtype, param_dtype) -> nn.Module:
    """Return the flax module computing logits for this model."""
    raise NotImplementedError

  def loss_function(self, build_network_result: BuildNetworkResult, labels):
    raise NotImplementedError

  def accuracy_function(self, build_network_result: BuildNetworkResult,
                        labels):
    raise NotImplementedError

  def postprocess(self, results: dict) -> dict:
    """Hook to postprocess eval results (ref :121-124)."""
    return results

  def reached_target(self) -> bool:
    return False


class _CNNModule(nn.Module):
  """Linen wrapper running a CNNModel's ``add_inference`` through a builder.

  Equivalent of the reference's ``cg/`` variable-scope + ConvNetBuilder
  instantiation (ref: models/model.py:239-276), as one compact module so
  XLA sees a single fusable graph.
  """
  model: Any
  nclass: int
  phase_train: bool
  data_format: str = "NHWC"
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, images):
    if self.data_format == "NCHW" and images.shape[-1] <= 4:
      # Inputs arrive NHWC from the data layer; transpose into the
      # requested compute layout (ref: CNNModel NCHW transpose,
      # models/model.py:239-276).
      images = jnp.transpose(images, (0, 3, 1, 2))
    cnn = builder_lib.ConvNetBuilder(
        input_layer=images,
        phase_train=self.phase_train,
        data_format=self.data_format,
        dtype=self.dtype,
        param_dtype=self.param_dtype,
    )
    self.model.add_inference(cnn)
    if not self.model.skip_final_affine_layer():
      # Restored final classifier layer (see module docstring).
      logits = cnn.affine(self.nclass, activation="linear")
    else:
      logits = cnn.top_layer
    aux_logits = None
    if cnn.aux_top_layer is not None:
      with cnn.switch_to_aux_top_layer():
        aux_logits = cnn.affine(self.nclass, activation="linear")
    logits = logits.astype(jnp.float32)
    if aux_logits is not None:
      aux_logits = aux_logits.astype(jnp.float32)
    return logits, aux_logits


class CNNModel(Model):
  """Convolutional model base (ref: models/model.py:134-312)."""

  def __init__(self, name, image_size, batch_size, learning_rate,
               layer_counts=None, fp16_loss_scale=128.0, params=None,
               depth=3, label_smoothing=0.0):
    super().__init__(name, batch_size, learning_rate,
                     fp16_loss_scale=fp16_loss_scale, params=params)
    self.image_size = image_size
    self.depth = depth
    self.layer_counts = layer_counts
    self.label_smoothing = label_smoothing

  def skip_final_affine_layer(self) -> bool:
    """Subclasses that build their own classifier return True (ref :241-249)."""
    return False

  def add_inference(self, cnn) -> None:
    """Build the network body with the ConvNetBuilder (ref :251-258)."""
    raise NotImplementedError

  def get_input_shapes(self, subset: str):
    del subset
    n = self.get_batch_size()
    # NHWC: the TPU-native layout (reference defaults NCHW for cuDNN).
    return [[n, self.image_size, self.image_size, self.depth], [n]]

  def get_input_data_types(self, subset: str):
    del subset
    return [jnp.float32, jnp.int32]

  def get_synthetic_inputs(self, rng, nclass: int):
    """Truncated-normal device-resident synthetic batch (ref :220-237)."""
    image_shape, label_shape = self.get_input_shapes("train")
    r_img, r_lbl = jax.random.split(rng)
    # Within [0, 255]: mean 127, stddev 60 (ref: models/model.py:220-237).
    images = jax.random.truncated_normal(
        r_img, -2.0, 2.0, image_shape, jnp.float32) * 60.0 + 127.0
    labels = jax.random.randint(r_lbl, label_shape, 0, nclass, jnp.int32)
    return images, labels

  def make_module(self, nclass, phase_train, data_format="NHWC",
                  dtype=jnp.float32, param_dtype=jnp.float32) -> nn.Module:
    return _CNNModule(model=self, nclass=nclass, phase_train=phase_train,
                      data_format=data_format, dtype=dtype,
                      param_dtype=param_dtype)

  def loss_function(self, build_network_result: BuildNetworkResult, labels):
    """Sparse softmax cross-entropy, + 0.4-weighted aux head (ref :287-302)."""
    logits, aux_logits = build_network_result.logits
    labels_onehot = jax.nn.one_hot(labels, logits.shape[-1],
                                   dtype=logits.dtype)
    if self.label_smoothing:
      n = logits.shape[-1]
      labels_onehot = (labels_onehot * (1.0 - self.label_smoothing)
                       + self.label_smoothing / n)
    xent = -jnp.sum(labels_onehot * jax.nn.log_softmax(logits), axis=-1)
    loss = jnp.mean(xent)
    if aux_logits is not None:
      aux_xent = -jnp.sum(
          labels_onehot * jax.nn.log_softmax(aux_logits), axis=-1)
      loss = loss + 0.4 * jnp.mean(aux_xent)
    return loss

  def accuracy_function(self, build_network_result: BuildNetworkResult,
                        labels):
    """top-1 / top-5 fractions (ref :305-312)."""
    logits, _ = build_network_result.logits
    top1 = jnp.mean((jnp.argmax(logits, axis=-1) == labels)
                    .astype(jnp.float32))
    top5_pred = jax.lax.top_k(logits, min(5, logits.shape[-1]))[1]
    top5 = jnp.mean(jnp.any(top5_pred == labels[:, None], axis=-1)
                    .astype(jnp.float32))
    return {"top_1_accuracy": top1, "top_5_accuracy": top5}
