"""Inception v3 / v4 model configurations (ref: models/inception_model.py).

Szegedy et al., "Rethinking the Inception Architecture for Computer
Vision" (arXiv:1512.00567) and "Inception-v4, Inception-ResNet and the
Impact of Residual Connections on Learning" (arXiv:1602.07261).
"""

from kf_benchmarks_tpu.models import model


class Inceptionv3Model(model.CNNModel):
  """InceptionV3, optional auxiliary head (ref: models/inception_model.py:44-124)."""

  def __init__(self, auxiliary=False, params=None):
    self._auxiliary = auxiliary
    super().__init__("inception3", 299, 32, 0.005, params=params)

  def add_inference(self, cnn):
    def inception_v3_a(cnn, n):
      cols = [[("conv", 64, 1, 1)],
              [("conv", 48, 1, 1), ("conv", 64, 5, 5)],
              [("conv", 64, 1, 1), ("conv", 96, 3, 3), ("conv", 96, 3, 3)],
              [("apool", 3, 3, 1, 1, "SAME"), ("conv", n, 1, 1)]]
      cnn.inception_module("incept_v3_a", cols)

    def inception_v3_b(cnn):
      cols = [[("conv", 384, 3, 3, 2, 2, "VALID")],
              [("conv", 64, 1, 1),
               ("conv", 96, 3, 3),
               ("conv", 96, 3, 3, 2, 2, "VALID")],
              [("mpool", 3, 3, 2, 2, "VALID")]]
      cnn.inception_module("incept_v3_b", cols)

    def inception_v3_c(cnn, n):
      cols = [[("conv", 192, 1, 1)],
              [("conv", n, 1, 1), ("conv", n, 1, 7), ("conv", 192, 7, 1)],
              [("conv", n, 1, 1), ("conv", n, 7, 1), ("conv", n, 1, 7),
               ("conv", n, 7, 1), ("conv", 192, 1, 7)],
              [("apool", 3, 3, 1, 1, "SAME"), ("conv", 192, 1, 1)]]
      cnn.inception_module("incept_v3_c", cols)

    def inception_v3_d(cnn):
      cols = [[("conv", 192, 1, 1), ("conv", 320, 3, 3, 2, 2, "VALID")],
              [("conv", 192, 1, 1), ("conv", 192, 1, 7), ("conv", 192, 7, 1),
               ("conv", 192, 3, 3, 2, 2, "VALID")],
              [("mpool", 3, 3, 2, 2, "VALID")]]
      cnn.inception_module("incept_v3_d", cols)

    def inception_v3_e(cnn, pooltype):
      cols = [[("conv", 320, 1, 1)],
              [("conv", 384, 1, 1), ("conv", 384, 1, 3)],
              [("share",), ("conv", 384, 3, 1)],
              [("conv", 448, 1, 1), ("conv", 384, 3, 3), ("conv", 384, 1, 3)],
              [("share",), ("share",), ("conv", 384, 3, 1)],
              [("mpool" if pooltype == "max" else "apool", 3, 3, 1, 1,
                "SAME"),
               ("conv", 192, 1, 1)]]
      cnn.inception_module("incept_v3_e", cols)

    def incept_v3_aux(cnn):
      assert cnn.aux_top_layer is None
      cnn.aux_top_layer = cnn.top_layer
      cnn.aux_top_size = cnn.top_size
      with cnn.switch_to_aux_top_layer():
        cnn.apool(5, 5, 3, 3, mode="VALID")
        cnn.conv(128, 1, 1, mode="SAME")
        cnn.conv(768, 5, 5, mode="VALID", stddev=0.01)
        cnn.reshape([-1, 768])

    cnn.use_batch_norm = True
    cnn.conv(32, 3, 3, 2, 2, mode="VALID")   # 299 x 299 x 3
    cnn.conv(32, 3, 3, 1, 1, mode="VALID")   # 149 x 149 x 32
    cnn.conv(64, 3, 3, 1, 1, mode="SAME")    # 147 x 147 x 64
    cnn.mpool(3, 3, 2, 2, mode="VALID")      # 147 x 147 x 64
    cnn.conv(80, 1, 1, 1, 1, mode="VALID")   # 73 x 73 x 80
    cnn.conv(192, 3, 3, 1, 1, mode="VALID")  # 71 x 71 x 192
    cnn.mpool(3, 3, 2, 2, "VALID")           # 35 x 35 x 192
    inception_v3_a(cnn, 32)                  # mixed
    inception_v3_a(cnn, 64)                  # mixed_1
    inception_v3_a(cnn, 64)                  # mixed_2
    inception_v3_b(cnn)                      # mixed_3
    inception_v3_c(cnn, 128)                 # mixed_4
    inception_v3_c(cnn, 160)                 # mixed_5
    inception_v3_c(cnn, 160)                 # mixed_6
    inception_v3_c(cnn, 192)                 # mixed_7
    if self._auxiliary:
      incept_v3_aux(cnn)                     # auxiliary head logits
    inception_v3_d(cnn)                      # mixed_8
    inception_v3_e(cnn, "avg")               # mixed_9
    inception_v3_e(cnn, "max")               # mixed_10
    cnn.apool(8, 8, 1, 1, "VALID")
    cnn.reshape([-1, 2048])


# Stem modules (ref: models/inception_model.py:126-160)
def inception_v4_sa(cnn):
  cols = [[("mpool", 3, 3, 2, 2, "VALID")],
          [("conv", 96, 3, 3, 2, 2, "VALID")]]
  cnn.inception_module("incept_v4_sa", cols)


def inception_v4_sb(cnn):
  cols = [[("conv", 64, 1, 1), ("conv", 96, 3, 3, 1, 1, "VALID")],
          [("conv", 64, 1, 1), ("conv", 64, 7, 1), ("conv", 64, 1, 7),
           ("conv", 96, 3, 3, 1, 1, "VALID")]]
  cnn.inception_module("incept_v4_sb", cols)


def inception_v4_sc(cnn):
  cols = [[("conv", 192, 3, 3, 2, 2, "VALID")],
          [("mpool", 3, 3, 2, 2, "VALID")]]
  cnn.inception_module("incept_v4_sc", cols)


# Reduction modules (ref: models/inception_model.py:146-160)
def inception_v4_ra(cnn, k, l, m, n):
  cols = [[("mpool", 3, 3, 2, 2, "VALID")],
          [("conv", n, 3, 3, 2, 2, "VALID")],
          [("conv", k, 1, 1), ("conv", l, 3, 3),
           ("conv", m, 3, 3, 2, 2, "VALID")]]
  cnn.inception_module("incept_v4_ra", cols)


def inception_v4_rb(cnn):
  cols = [[("mpool", 3, 3, 2, 2, "VALID")],
          [("conv", 192, 1, 1), ("conv", 192, 3, 3, 2, 2, "VALID")],
          [("conv", 256, 1, 1), ("conv", 256, 1, 7), ("conv", 320, 7, 1),
           ("conv", 320, 3, 3, 2, 2, "VALID")]]
  cnn.inception_module("incept_v4_rb", cols)


class Inceptionv4Model(model.CNNModel):
  """InceptionV4 (ref: models/inception_model.py:162-209)."""

  def __init__(self, params=None):
    super().__init__("inception4", 299, 32, 0.005, params=params)

  def add_inference(self, cnn):
    def inception_v4_a(cnn):
      cols = [[("apool", 3, 3, 1, 1, "SAME"), ("conv", 96, 1, 1)],
              [("conv", 96, 1, 1)],
              [("conv", 64, 1, 1), ("conv", 96, 3, 3)],
              [("conv", 64, 1, 1), ("conv", 96, 3, 3), ("conv", 96, 3, 3)]]
      cnn.inception_module("incept_v4_a", cols)

    def inception_v4_b(cnn):
      cols = [[("apool", 3, 3, 1, 1, "SAME"), ("conv", 128, 1, 1)],
              [("conv", 384, 1, 1)],
              [("conv", 192, 1, 1), ("conv", 224, 1, 7), ("conv", 256, 7, 1)],
              [("conv", 192, 1, 1), ("conv", 192, 1, 7), ("conv", 224, 7, 1),
               ("conv", 224, 1, 7), ("conv", 256, 7, 1)]]
      cnn.inception_module("incept_v4_b", cols)

    def inception_v4_c(cnn):
      cols = [[("apool", 3, 3, 1, 1, "SAME"), ("conv", 256, 1, 1)],
              [("conv", 256, 1, 1)],
              [("conv", 384, 1, 1), ("conv", 256, 1, 3)],
              [("share",), ("conv", 256, 3, 1)],
              [("conv", 384, 1, 1), ("conv", 448, 1, 3), ("conv", 512, 3, 1),
               ("conv", 256, 3, 1)],
              [("share",), ("share",), ("share",), ("conv", 256, 1, 3)]]
      cnn.inception_module("incept_v4_c", cols)

    cnn.use_batch_norm = True
    cnn.conv(32, 3, 3, 2, 2, mode="VALID")
    cnn.conv(32, 3, 3, 1, 1, mode="VALID")
    cnn.conv(64, 3, 3)
    inception_v4_sa(cnn)
    inception_v4_sb(cnn)
    inception_v4_sc(cnn)
    for _ in range(4):
      inception_v4_a(cnn)
    inception_v4_ra(cnn, 192, 224, 256, 384)
    for _ in range(7):
      inception_v4_b(cnn)
    inception_v4_rb(cnn)
    for _ in range(3):
      inception_v4_c(cnn)
    cnn.spatial_mean()
    cnn.dropout(0.8)
