"""DenseNet model configurations, cifar-sized (ref: models/densenet_model.py).

Huang et al., "Densely Connected Convolutional Networks"
(arXiv:1608.06993).
"""

import math

import jax.nn
import jax.numpy as jnp

from kf_benchmarks_tpu.models import model as model_lib


class DensenetCifar10Model(model_lib.CNNModel):
  """Densenet for cifar10 (ref: models/densenet_model.py:27-85)."""

  def __init__(self, model, layer_counts, growth_rate, params=None):
    self.growth_rate = growth_rate
    super().__init__(model, 32, 64, 0.1, layer_counts=layer_counts,
                     params=params)
    self.batch_norm_config = {"decay": 0.9, "epsilon": 1e-5, "scale": True}

  def dense_block(self, cnn, growth_rate):
    """BN -> relu -> 3x3 conv, concatenated onto the input
    (ref: models/densenet_model.py:36-44)."""
    input_layer = cnn.top_layer
    c = cnn.batch_norm(input_layer, **self.batch_norm_config)
    c = jax.nn.relu(c)
    c = cnn.conv(growth_rate, 3, 3, 1, 1,
                 stddev=math.sqrt(2.0 / 9 / growth_rate),
                 activation=None, input_layer=c)
    cnn.top_layer = jnp.concatenate([input_layer, c], cnn.channel_axis)
    cnn.top_size += growth_rate

  def transition_layer(self, cnn):
    """BN -> relu -> 1x1 conv -> 2x2 avg pool (ref :46-51)."""
    in_size = cnn.top_size
    cnn.batch_norm(**self.batch_norm_config)
    cnn.top_layer = jax.nn.relu(cnn.top_layer)
    cnn.conv(in_size, 1, 1, 1, 1, stddev=math.sqrt(2.0 / 9 / in_size))
    cnn.apool(2, 2, 2, 2)

  def add_inference(self, cnn):
    if self.layer_counts is None:
      raise ValueError(f"Layer counts not specified for {self.get_name()}")
    if self.growth_rate is None:
      raise ValueError(f"Growth rate not specified for {self.get_name()}")

    cnn.conv(16, 3, 3, 1, 1, activation=None)
    for _ in range(self.layer_counts[0]):
      self.dense_block(cnn, self.growth_rate)
    self.transition_layer(cnn)
    for _ in range(self.layer_counts[1]):
      self.dense_block(cnn, self.growth_rate)
    self.transition_layer(cnn)
    for _ in range(self.layer_counts[2]):
      self.dense_block(cnn, self.growth_rate)
    cnn.batch_norm(**self.batch_norm_config)
    cnn.top_layer = jax.nn.relu(cnn.top_layer)
    cnn.top_size = cnn.top_layer.shape[cnn.channel_axis]
    cnn.spatial_mean()

  def get_learning_rate(self, global_step, batch_size):
    """Piecewise 0.1/0.01/0.001/0.0001 at epochs 150/225/300
    (ref: models/densenet_model.py:78-85)."""
    num_batches_per_epoch = int(50000 / batch_size)
    step = jnp.asarray(global_step, jnp.int32)
    lr = jnp.asarray(0.1, jnp.float32)
    for epoch, value in zip((150, 225, 300), (0.01, 0.001, 0.0001)):
      lr = jnp.where(step >= epoch * num_batches_per_epoch,
                     jnp.asarray(value, jnp.float32), lr)
    return lr


def create_densenet40_k12_model(params=None):
  return DensenetCifar10Model("densenet40_k12", (12, 12, 12), 12,
                              params=params)


def create_densenet100_k12_model(params=None):
  return DensenetCifar10Model("densenet100_k12", (32, 32, 32), 12,
                              params=params)


def create_densenet100_k24_model(params=None):
  return DensenetCifar10Model("densenet100_k24", (32, 32, 32), 24,
                              params=params)
