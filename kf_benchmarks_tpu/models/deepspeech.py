"""DeepSpeech2 (speech recognition), TPU-native flax implementation.

Capability parity with the reference's experimental DeepSpeech2 model
(ref: scripts/tf_cnn_benchmarks/models/experimental/deepspeech.py:
121-441): two conv+BN layers over the spectrogram, five (bidirectional)
RNN layers with inter-layer batch norm, a batch-normed dense projection
to the 29-character vocabulary, CTC loss, and a greedy decoder with
WER/CER metrics (ref :28-120 DeepSpeechDecoder).

TPU-first choices: the RNN stack runs under ``lax.scan`` via flax's
``nn.RNN`` (static shapes, compiler-schedulable), and CTC uses
``optax.ctc_loss`` instead of the reference's sparse-tensor TF op. The
sequence dimension stays padded-dense with explicit length masks -- the
analog of the reference's padded-batch + ``ctc_input_length`` plumbing
(ref :359-395).
"""

from __future__ import annotations

import itertools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import flax.linen as nn
import optax

from kf_benchmarks_tpu.models import model as model_lib
from kf_benchmarks_tpu.models.builder import BatchNorm

SPEECH_LABELS = " abcdefghijklmnopqrstuvwxyz'-"
BLANK_INDEX = 28  # ref: DeepSpeechDecoder(labels, blank_index=28)


class DeepSpeechDecoder:
  """Greedy CTC decoder + WER/CER (ref: deepspeech.py:28-120)."""

  def __init__(self, labels: str = SPEECH_LABELS,
               blank_index: int = BLANK_INDEX):
    self.labels = labels
    self.blank_index = blank_index
    self.int_to_char = dict(enumerate(labels))

  def convert_to_string(self, sequence) -> str:
    return "".join(self.int_to_char[int(i)] for i in sequence)

  def decode(self, char_indexes) -> str:
    """Labels -> transcript (drops padding/blank)."""
    return self.convert_to_string(
        [i for i in np.asarray(char_indexes).ravel()
         if 0 <= int(i) < len(self.labels) and int(i) != self.blank_index])

  def decode_logits(self, probs) -> str:
    """Greedy path: argmax per frame, collapse repeats, drop blanks."""
    best = np.argmax(np.asarray(probs), axis=-1)
    merged = [k for k, _ in itertools.groupby(best)]
    return self.convert_to_string(
        [k for k in merged if int(k) != self.blank_index])

  @staticmethod
  def _levenshtein(a, b) -> int:
    if len(a) < len(b):
      a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
      curr = [i]
      for j, cb in enumerate(b, 1):
        curr.append(min(prev[j] + 1, curr[j - 1] + 1,
                        prev[j - 1] + (ca != cb)))
      prev = curr
    return prev[-1]

  def wer(self, decode: str, target: str) -> float:
    return float(self._levenshtein(decode.split(), target.split()))

  def cer(self, decode: str, target: str) -> float:
    return float(self._levenshtein(list(decode), list(target)))


class _DS2Module(nn.Module):
  """conv x2 -> (bi)RNN x5 -> BN -> dense (ref: build_network :301-357)."""

  nclass: int
  phase_train: bool
  num_rnn_layers: int = 5
  rnn_type: str = "lstm"
  is_bidirectional: bool = True
  rnn_hidden_size: int = 800
  use_bias: bool = True
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32

  def _bn(self, x):
    return BatchNorm(use_running_average=not self.phase_train,
                            momentum=0.997, epsilon=1e-5, use_scale=True,
                            use_bias=True, dtype=self.dtype,
                            param_dtype=self.param_dtype)(x)

  def _conv_bn(self, x, kernel, strides, padding):
    x = jnp.pad(x, ((0, 0), (padding[0], padding[0]),
                    (padding[1], padding[1]), (0, 0)))
    x = nn.Conv(32, kernel, strides=strides, padding="VALID",
                use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype)(x)
    return nn.relu(self._bn(x))

  def _cell(self):
    if self.rnn_type == "gru":
      return nn.GRUCell(self.rnn_hidden_size, dtype=self.dtype,
                        param_dtype=self.param_dtype)
    if self.rnn_type == "rnn":
      return nn.SimpleCell(self.rnn_hidden_size, dtype=self.dtype,
                           param_dtype=self.param_dtype)
    if self.rnn_type == "lstm":
      return nn.OptimizedLSTMCell(self.rnn_hidden_size, dtype=self.dtype,
                                  param_dtype=self.param_dtype)
    raise ValueError(f"Unsupported rnn type {self.rnn_type!r}")

  def _initial_carry(self, x):
    """Zero carry derived from the (possibly replica-varying) input so the
    scan carry has the same varying-manual-axes type as the body output
    under shard_map (jax VMA check; plain zeros would be unvarying)."""
    zero = jnp.zeros((x.shape[0], self.rnn_hidden_size), x.dtype) \
        + 0.0 * x[:, 0, :1]
    return (zero, zero) if self.rnn_type == "lstm" else zero

  def _rnn_layer(self, x, use_batch_norm):
    """(ref: _rnn_layer :230-270): optional pre-BN; fw (+bw concat)."""
    if use_batch_norm:
      x = self._bn(x)
    fw = nn.RNN(self._cell())(x, initial_carry=self._initial_carry(x))
    if not self.is_bidirectional:
      return fw
    bw = nn.RNN(self._cell(), reverse=True, keep_order=True)(
        x, initial_carry=self._initial_carry(x))
    return jnp.concatenate([fw, bw], axis=-1)

  @nn.compact
  def __call__(self, spectrogram):
    x = spectrogram.astype(self.dtype)
    x = self._conv_bn(x, (41, 11), (2, 2), (20, 5))
    x = self._conv_bn(x, (21, 11), (2, 1), (10, 5))
    b, t, f, c = x.shape
    x = x.reshape(b, t, f * c)
    for layer in range(self.num_rnn_layers):
      x = self._rnn_layer(x, use_batch_norm=layer != 0)
    x = self._bn(x)
    logits = nn.Dense(self.nclass, use_bias=self.use_bias,
                      dtype=self.dtype, param_dtype=self.param_dtype)(x)
    return logits.astype(jnp.float32), None


class DeepSpeech2Model(model_lib.Model):
  """(ref: deepspeech.py:121-441)."""

  CONV_FILTERS = 32
  # optax.ctc_loss scans with constant-seeded carries; see the
  # check_vma scoping note in train_step.make_step_fns.
  relax_shard_map_vma = True

  def __init__(self, num_rnn_layers=5, rnn_type="lstm",
               is_bidirectional=True, rnn_hidden_size=800, use_bias=True,
               params=None):
    super().__init__("deepspeech2", batch_size=128, learning_rate=0.0005,
                     fp16_loss_scale=128, params=params)
    self.num_rnn_layers = num_rnn_layers
    self.rnn_type = rnn_type
    self.is_bidirectional = is_bidirectional
    self.rnn_hidden_size = rnn_hidden_size
    self.use_bias = use_bias
    self.num_feature_bins = 161
    self.max_time_steps = 3494
    self.max_label_length = 576

  def make_module(self, nclass, phase_train, data_format="NHWC",
                  dtype=jnp.float32, param_dtype=jnp.float32):
    del data_format
    return _DS2Module(nclass=nclass, phase_train=phase_train,
                      num_rnn_layers=self.num_rnn_layers,
                      rnn_type=self.rnn_type,
                      is_bidirectional=self.is_bidirectional,
                      rnn_hidden_size=self.rnn_hidden_size,
                      use_bias=self.use_bias, dtype=dtype,
                      param_dtype=param_dtype)

  # -- inputs (ref :272-297) ------------------------------------------------

  def get_input_shapes(self, subset):
    n = self.get_batch_size()
    return [[n, self.max_time_steps, self.num_feature_bins, 1],
            [n, self.max_label_length], [n], [n]]

  def get_input_data_types(self, subset):
    return [jnp.float32, jnp.int32, jnp.int32, jnp.int32]

  def get_synthetic_inputs(self, rng, nclass):
    shapes = self.get_input_shapes("train")
    r_spec, r_lbl = jax.random.split(rng)
    spectrogram = jax.random.uniform(r_spec, shapes[0], jnp.float32)
    labels = jax.random.randint(r_lbl, shapes[1], 0, BLANK_INDEX,
                                jnp.int32)
    input_lengths = jnp.full(shapes[2], self.max_time_steps, jnp.int32)
    label_lengths = jnp.full(shapes[3], self.max_label_length, jnp.int32)
    return spectrogram, (labels, input_lengths, label_lengths)

  # -- loss (ref :359-395) --------------------------------------------------

  def loss_function(self, build_network_result, labels):
    logits, _ = build_network_result.logits
    target_labels, input_lengths, label_lengths = labels
    ctc_time_steps = logits.shape[1]
    # Scale the true utterance length onto the downsampled frame axis
    # (ref: ctc_input_length arithmetic :371-377).
    ctc_input_length = jnp.floor(
        input_lengths.astype(jnp.float32) * ctc_time_steps /
        float(self.max_time_steps)).astype(jnp.int32)
    frame_idx = jnp.arange(ctc_time_steps)[None, :]
    logit_paddings = (frame_idx >= ctc_input_length[:, None]) \
        .astype(jnp.float32)
    label_idx = jnp.arange(target_labels.shape[1])[None, :]
    label_paddings = (label_idx >= label_lengths[:, None]) \
        .astype(jnp.float32)
    losses = optax.ctc_loss(logits, logit_paddings,
                            target_labels.astype(jnp.int32),
                            label_paddings, blank_id=BLANK_INDEX)
    return jnp.mean(losses)

  # -- eval (ref :401-441) --------------------------------------------------

  def accuracy_function(self, build_network_result, labels):
    logits, _ = build_network_result.logits
    probs = jax.nn.softmax(logits)
    target_labels = labels[0]
    # Scalar proxy for the shared loop (greedy frame accuracy on
    # non-blank frames); the per-frame arrays feed postprocess WER/CER.
    pred = jnp.argmax(probs, axis=-1)
    return {"top_1_accuracy": jnp.mean((pred != BLANK_INDEX)
                                       .astype(jnp.float32)),
            "top_5_accuracy": jnp.zeros(()),
            "deepspeech2_prob": probs,
            "deepspeech2_label": target_labels}

  def postprocess(self, results):
    """WER/CER over accumulated probs/labels (ref :413-441)."""
    if "deepspeech2_prob" not in results:
      return results
    decoder = DeepSpeechDecoder()
    probs = np.asarray(results["deepspeech2_prob"])
    targets = np.asarray(results["deepspeech2_label"])
    total_wer = total_cer = 0.0
    n = probs.shape[0]
    for i in range(n):
      predicted = decoder.decode_logits(probs[i])
      expected = decoder.decode(targets[i])
      total_cer += decoder.cer(predicted, expected) / max(len(expected), 1)
      total_wer += decoder.wer(predicted, expected) / max(
          len(expected.split()), 1)
    results["CER"] = total_cer / max(n, 1)
    results["WER"] = total_wer / max(n, 1)
    return results


def create_deepspeech2_model(params=None):
  return DeepSpeech2Model(params=params)
