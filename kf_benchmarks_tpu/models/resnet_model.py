"""ResNet v1 / v1.5 / v2 for ImageNet and Cifar.

TPU-native re-design of the reference ResNet (ref:
scripts/tf_cnn_benchmarks/models/resnet_model.py:41-485): bottleneck /
residual blocks expressed through the ConvNetBuilder, per-model default
batch sizes, 0.1@bs256-scaled piecewise LR at epochs [30,60,80,90] with
5-epoch linear warmup (ref :279-363), and cifar resnet20-110 variants
(ref :392-485).

Versions:
  v1   -- stride-2 in the first 1x1 of the bottleneck (original paper).
  v1.5 -- stride-2 moved to the 3x3 (the reference's default resnet50;
          ref :97-116 "ResNet V1.5").
  v2   -- preactivation (BN+ReLU before convs), identity shortcut add.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from kf_benchmarks_tpu.models import model

IMAGENET_NUM_TRAIN_IMAGES = 1281167


def bottleneck_block(cnn, depth: int, depth_bottleneck: int, stride: int,
                     version: str):
  """Bottleneck residual unit with 3 sub-layers (ref :41-170)."""
  input_layer = cnn.top_layer
  in_size = cnn.top_size
  name_key = "resnet_v2" if version == "v2" else "resnet_v1"
  name = f"{name_key}{cnn.counts[name_key]}"
  cnn.counts[name_key] += 1

  if version == "v2":
    preact = cnn.batch_norm(name=name + "_preact_bn")
    preact = _relu(cnn, preact)
  else:
    preact = input_layer

  if in_size != depth or stride != 1:
    # Projection shortcut (ref :58-76): 1x1 conv, no activation.
    shortcut = cnn.conv(depth, 1, 1, stride, stride, mode="SAME_RESNET",
                        input_layer=preact if version == "v2" else input_layer,
                        num_channels_in=in_size, use_batch_norm=(version != "v2"),
                        activation=None, bias=None, name=name + "_shortcut")
  else:
    shortcut = input_layer

  body_in = preact if version == "v2" else input_layer
  if version == "v1":
    s1, s3 = stride, 1  # stride in the first 1x1 (ref :77-96)
  else:
    s1, s3 = 1, stride  # stride in the 3x3: v1.5 and v2 (ref :97-170)
  use_bn = version != "v2"
  x = cnn.conv(depth_bottleneck, 1, 1, s1, s1, input_layer=body_in,
               num_channels_in=in_size, use_batch_norm=use_bn,
               activation="relu" if use_bn else None, bias=None,
               name=name + "_a")
  if version == "v2":
    x = cnn.batch_norm(name=name + "_a_bn")
    x = _relu(cnn, x)
  x = cnn.conv(depth_bottleneck, 3, 3, s3, s3, mode="SAME_RESNET",
               use_batch_norm=use_bn,
               activation="relu" if use_bn else None, bias=None,
               name=name + "_b")
  if version == "v2":
    x = cnn.batch_norm(name=name + "_b_bn")
    x = _relu(cnn, x)
  x = cnn.conv(depth, 1, 1, 1, 1, use_batch_norm=use_bn, activation=None,
               bias=None, name=name + "_c")
  out = x + shortcut
  if version != "v2":
    out = _relu(cnn, out)
  cnn.top_layer = out
  cnn.top_size = depth
  return out


def residual_block(cnn, depth: int, stride: int, version: str):
  """Two-3x3 residual unit for cifar resnets (ref :173-277)."""
  input_layer = cnn.top_layer
  in_size = cnn.top_size
  name = f"resblk{cnn.counts['resblk']}"
  cnn.counts["resblk"] += 1

  if version == "v2":
    preact = cnn.batch_norm(name=name + "_preact_bn")
    preact = _relu(cnn, preact)
    body_in = preact
  else:
    body_in = input_layer

  if in_size != depth or stride != 1:
    shortcut = cnn.conv(depth, 1, 1, stride, stride, mode="SAME_RESNET",
                        input_layer=body_in, num_channels_in=in_size,
                        use_batch_norm=(version != "v2"), activation=None,
                        bias=None, name=name + "_shortcut")
  else:
    shortcut = input_layer

  use_bn = version != "v2"
  x = cnn.conv(depth, 3, 3, stride, stride, mode="SAME_RESNET",
               input_layer=body_in, num_channels_in=in_size,
               use_batch_norm=use_bn,
               activation="relu" if use_bn else None, bias=None,
               name=name + "_a")
  if version == "v2":
    x = cnn.batch_norm(name=name + "_a_bn")
    x = _relu(cnn, x)
  x = cnn.conv(depth, 3, 3, 1, 1, use_batch_norm=use_bn, activation=None,
               bias=None, name=name + "_b")
  out = x + shortcut
  if version != "v2":
    out = _relu(cnn, out)
  cnn.top_layer = out
  cnn.top_size = depth
  return out


def _relu(cnn, x):
  import flax.linen as nn
  out = nn.relu(x)
  cnn.top_layer = out
  return out


class ResnetModel(model.CNNModel):
  """ImageNet ResNet (ref :279-363)."""

  def __init__(self, model_name: str, layer_counts, params=None):
    # Per-model default batch sizes (ref :285-299).
    default_batch_sizes = {
        "resnet50": 64, "resnet101": 32, "resnet152": 32,
        "resnet50_v1.5": 64, "resnet101_v1.5": 32,
        "resnet50_v2": 64, "resnet101_v2": 32, "resnet152_v2": 32,
    }
    batch_size = default_batch_sizes.get(model_name, 32)
    super().__init__(model_name, 224, batch_size, 0.1,
                     layer_counts=layer_counts, params=params)
    if "v2" in model_name:
      self.version = "v2"
    elif "v1.5" in model_name:
      self.version = "v1.5"
    else:
      # The reference's plain 'resnet50' is v1.5 semantics (stride in the
      # 3x3); true v1 is available as version override (ref :97-116).
      self.version = "v1.5"

  def add_inference(self, cnn):
    if self.layer_counts is None:
      raise ValueError(f"Layer counts not specified for {self.get_name()}")
    cnn.use_batch_norm = self.version != "v2"
    cnn.batch_norm_config = {"decay": 0.9, "epsilon": 1e-5, "scale": True}
    cnn.conv(64, 7, 7, 2, 2, mode="SAME_RESNET",
             use_batch_norm=(self.version != "v2"), activation="relu",
             bias=None, name="conv_stem")
    cnn.mpool(3, 3, 2, 2, mode="SAME")
    for i, (count, depth_bottleneck, depth) in enumerate(
        zip(self.layer_counts, (64, 128, 256, 512),
            (256, 512, 1024, 2048))):
      for j in range(count):
        stride = 2 if (j == 0 and i > 0) else 1
        bottleneck_block(cnn, depth, depth_bottleneck, stride, self.version)
    if self.version == "v2":
      cnn.batch_norm(name="final_bn")
      _relu(cnn, cnn.top_layer)
    cnn.spatial_mean()

  def get_learning_rate(self, global_step, batch_size):
    """0.1@bs256-scaled piecewise [30,60,80,90] + 5-epoch warmup
    (ref :340-363)."""
    num_batches_per_epoch = IMAGENET_NUM_TRAIN_IMAGES / float(batch_size)
    rescaled_lr = 0.1 * batch_size / 256.0
    boundaries = np.array([30, 60, 80, 90]) * num_batches_per_epoch
    values = rescaled_lr * np.array([1.0, 0.1, 0.01, 0.001, 1e-4])
    step = jnp.asarray(global_step, jnp.float32)
    lr = jnp.asarray(values[0], jnp.float32)
    for b, v in zip(boundaries, values[1:]):
      lr = jnp.where(step >= b, jnp.asarray(v, jnp.float32), lr)
    warmup_steps = int(5 * num_batches_per_epoch)
    warmup_lr = rescaled_lr * step / max(warmup_steps, 1)
    return jnp.where(step < warmup_steps, warmup_lr, lr)


def create_resnet50_model(params=None):
  return ResnetModel("resnet50", (3, 4, 6, 3), params=params)


def create_resnet50_v15_model(params=None):
  return ResnetModel("resnet50_v1.5", (3, 4, 6, 3), params=params)


def create_resnet50_v2_model(params=None):
  return ResnetModel("resnet50_v2", (3, 4, 6, 3), params=params)


def create_resnet101_model(params=None):
  return ResnetModel("resnet101", (3, 4, 23, 3), params=params)


def create_resnet101_v2_model(params=None):
  return ResnetModel("resnet101_v2", (3, 4, 23, 3), params=params)


def create_resnet152_model(params=None):
  return ResnetModel("resnet152", (3, 8, 36, 3), params=params)


def create_resnet152_v2_model(params=None):
  return ResnetModel("resnet152_v2", (3, 8, 36, 3), params=params)


class ResnetCifar10Model(model.CNNModel):
  """Cifar-10 ResNet-N, N in {20,32,44,56,110} (ref :392-485).

  Uses 3 stages of (N-2)/6 residual blocks with widths 16/32/64 and the
  reference's piecewise LR at epochs [82,123,300] (ref :462-485).
  """

  def __init__(self, model_name: str, layer_counts, params=None):
    self.version = "v2" if "v2" in model_name else "v1"
    super().__init__(model_name, 32, 128, 0.1, layer_counts=layer_counts,
                     params=params)

  def add_inference(self, cnn):
    if self.layer_counts is None:
      raise ValueError(f"Layer counts not specified for {self.get_name()}")
    cnn.use_batch_norm = self.version != "v2"
    cnn.batch_norm_config = {"decay": 0.9, "epsilon": 1e-5, "scale": True}
    cnn.conv(16, 3, 3, 1, 1, use_batch_norm=(self.version != "v2"),
             activation="relu" if self.version != "v2" else None,
             bias=None, name="conv_stem")
    for i, depth in enumerate((16, 32, 64)):
      for j in range(self.layer_counts[i]):
        stride = 2 if (j == 0 and i > 0) else 1
        residual_block(cnn, depth, stride, self.version)
    if self.version == "v2":
      cnn.batch_norm(name="final_bn")
      _relu(cnn, cnn.top_layer)
    cnn.spatial_mean()

  def get_learning_rate(self, global_step, batch_size):
    num_batches_per_epoch = 50000 // batch_size
    boundaries = num_batches_per_epoch * np.array([82, 123, 300])
    values = np.array([0.1, 0.01, 0.001, 0.0002])
    step = jnp.asarray(global_step, jnp.float32)
    lr = jnp.asarray(values[0], jnp.float32)
    for b, v in zip(boundaries, values[1:]):
      lr = jnp.where(step >= b, jnp.asarray(v, jnp.float32), lr)
    return lr


def _cifar_layer_counts(depth: int):
  n = (depth - 2) // 6
  return (n, n, n)


def create_resnet20_cifar_model(params=None):
  return ResnetCifar10Model("resnet20", _cifar_layer_counts(20), params)


def create_resnet20_v2_cifar_model(params=None):
  return ResnetCifar10Model("resnet20_v2", _cifar_layer_counts(20), params)


def create_resnet32_cifar_model(params=None):
  return ResnetCifar10Model("resnet32", _cifar_layer_counts(32), params)


def create_resnet32_v2_cifar_model(params=None):
  return ResnetCifar10Model("resnet32_v2", _cifar_layer_counts(32), params)


def create_resnet44_cifar_model(params=None):
  return ResnetCifar10Model("resnet44", _cifar_layer_counts(44), params)


def create_resnet44_v2_cifar_model(params=None):
  return ResnetCifar10Model("resnet44_v2", _cifar_layer_counts(44), params)


def create_resnet56_cifar_model(params=None):
  return ResnetCifar10Model("resnet56", _cifar_layer_counts(56), params)


def create_resnet56_v2_cifar_model(params=None):
  return ResnetCifar10Model("resnet56_v2", _cifar_layer_counts(56), params)


def create_resnet110_cifar_model(params=None):
  return ResnetCifar10Model("resnet110", _cifar_layer_counts(110), params)


def create_resnet110_v2_cifar_model(params=None):
  return ResnetCifar10Model("resnet110_v2", _cifar_layer_counts(110), params)
