"""ConvNetBuilder: imperative layer builder over flax.linen.

TPU-native re-design of the reference's ConvNetBuilder (ref:
scripts/tf_cnn_benchmarks/convnet_builder.py:29-468). Keeps the stateful
``top_layer``/``top_size`` + auto-naming imperative style that makes the
reference model zoo cheap to express, but each op instantiates flax
submodules inside the enclosing module's compact scope, so the whole
network is one traced function XLA can fuse and tile onto the MXU.

Layout: NHWC is the default (TPU-native); NCHW accepted for parity.
Reduced precision: activations/compute in ``dtype`` (bfloat16 on TPU when
--use_fp16), parameters in ``param_dtype`` (fp32 master copies), which is
the equivalent of the reference's fp16 custom-getter variable cast
(ref: convnet_builder.py:56-86).
"""

from __future__ import annotations

import contextlib
from collections import defaultdict
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import flax.linen as nn


def _activate(x, activation: Optional[str]):
  if activation in (None, "linear"):
    return x
  if activation == "relu":
    return nn.relu(x)
  if activation == "relu6":
    return nn.relu6(x)
  if activation == "tanh":
    return jnp.tanh(x)
  if activation == "sigmoid":
    return nn.sigmoid(x)
  raise KeyError(f"Invalid activation type {activation!r}")


class CompactBatchNorm(nn.Module):
  """Batch norm that keeps activations in the compute dtype.

  flax's nn.BatchNorm upcasts the full activation tensor to float32 for
  both the statistics and the normalize arithmetic; on TPU the resulting
  f32 activation traffic is pure HBM cost on a benchmark that is
  bandwidth-bound (see PERF.md). Here the statistics are still accumulated
  in float32 -- the upcast fuses into the reduction so the tensor is read
  once at compute precision -- and the normalize runs subtract-first in
  the compute dtype ((x - mean) * inv*scale + bias: the subtraction of
  nearby values is exact, preserving full relative precision on the
  normalized output), which XLA fuses with the neighboring ReLU/residual
  ops.

  Leaf layout matches nn.BatchNorm (params: scale/bias, batch_stats:
  mean/var, float32), so a checkpoint is interchangeable wherever the
  module is given an explicit name (the builder passes name=). Call
  sites that relied on nn.BatchNorm's auto-generated ``BatchNorm_N``
  scope names use the ``BatchNorm`` subclass below instead. Semantics
  match the reference's batch norm (ref: convnet_builder.py:408-462)
  with use_fast_variance statistics.
  """
  use_running_average: bool
  momentum: float = 0.999
  epsilon: float = 0.001
  use_scale: bool = False
  use_bias: bool = True
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, x):
    feat = x.shape[-1]
    ra_mean = self.variable("batch_stats", "mean",
                            lambda s: jnp.zeros(s, jnp.float32), (feat,))
    ra_var = self.variable("batch_stats", "var",
                           lambda s: jnp.ones(s, jnp.float32), (feat,))
    if self.use_running_average:
      mean, var = ra_mean.value, ra_var.value
    else:
      axes = tuple(range(x.ndim - 1))
      xf = x.astype(jnp.float32)
      mean = jnp.mean(xf, axes)
      mean2 = jnp.mean(jnp.square(xf), axes)
      var = jnp.maximum(mean2 - jnp.square(mean), 0.0)
      if not self.is_initializing():
        m = self.momentum
        ra_mean.value = m * ra_mean.value + (1 - m) * mean
        ra_var.value = m * ra_var.value + (1 - m) * var
    inv = jax.lax.rsqrt(var + self.epsilon)
    scale = jnp.ones((feat,), jnp.float32)
    if self.use_scale:
      scale = self.param("scale", nn.initializers.ones, (feat,),
                         self.param_dtype).astype(jnp.float32)
    bias = jnp.zeros((feat,), jnp.float32)
    if self.use_bias:
      bias = self.param("bias", nn.initializers.zeros, (feat,),
                        self.param_dtype).astype(jnp.float32)
    # Subtract-first normalize in the compute dtype:
    # y = (x - mean) * (inv*scale) + bias. Subtraction of nearby values
    # is exact in floating point, so this keeps full relative precision
    # on the O(1) normalized output; the folded y = x*a + b form loses
    # ~mean/std relative bits to cancellation of two rounded bf16
    # products when channel means are large.
    a = (inv * scale).astype(self.dtype)
    return ((x.astype(self.dtype) - mean.astype(self.dtype)) * a +
            bias.astype(self.dtype))


class BatchNorm(CompactBatchNorm):
  """Checkpoint-name-compatible alias: flax auto-names modules by class,
  so call sites that relied on nn.BatchNorm's auto-generated
  ``BatchNorm_N`` scope names (mobilenet/nasnet/deepspeech) use this
  subclass and keep their parameter tree layout."""


class ConvNetBuilder:
  """Builds a ConvNet anchored at ``self.top_layer`` (ref: convnet_builder.py:29)."""

  def __init__(self, input_layer, phase_train: bool, data_format: str = "NHWC",
               dtype=jnp.float32, param_dtype=jnp.float32,
               use_batch_norm: bool = False,
               batch_norm_config: Optional[dict] = None):
    if data_format not in ("NHWC", "NCHW"):
      raise ValueError(f"Invalid data_format {data_format!r}")
    self.data_format = data_format
    self.channel_axis = 3 if data_format == "NHWC" else 1
    self.top_layer = jnp.asarray(input_layer, dtype)
    self.top_size = int(input_layer.shape[self.channel_axis])
    self.phase_train = phase_train
    self.dtype = dtype
    self.param_dtype = param_dtype
    self.use_batch_norm = use_batch_norm
    # Reference batch-norm defaults (ref: convnet_builder.py:408-420).
    self.batch_norm_config = {"decay": 0.999, "epsilon": 0.001,
                              "scale": False}
    self.batch_norm_config.update(batch_norm_config or {})
    self.counts = defaultdict(int)
    self.aux_top_layer = None
    self.aux_top_size = 0

  # -- helpers -------------------------------------------------------------

  def _name(self, kind: str) -> str:
    n = self.counts[kind]
    self.counts[kind] += 1
    return f"{kind}{n}"

  def _spatial(self, x):
    if self.data_format == "NHWC":
      return x
    return jnp.transpose(x, (0, 2, 3, 1))  # to NHWC for the op

  def _unspatial(self, x):
    if self.data_format == "NHWC":
      return x
    return jnp.transpose(x, (0, 3, 1, 2))

  @contextlib.contextmanager
  def switch_to_aux_top_layer(self):
    """Context that redirects ops onto the auxiliary head
    (ref: convnet_builder.py:88-101)."""
    if self.aux_top_layer is None:
      raise RuntimeError("aux_top_layer not set")
    self.top_layer, self.aux_top_layer = self.aux_top_layer, self.top_layer
    self.top_size, self.aux_top_size = self.aux_top_size, self.top_size
    try:
      yield
    finally:
      self.top_layer, self.aux_top_layer = self.aux_top_layer, self.top_layer
      self.top_size, self.aux_top_size = self.aux_top_size, self.top_size

  # -- layers --------------------------------------------------------------

  def conv(self, num_out_channels: int, k_height: int, k_width: int,
           d_height: int = 1, d_width: int = 1, mode: str = "SAME",
           input_layer=None, num_channels_in: Optional[int] = None,
           use_batch_norm: Optional[bool] = None, stddev: Optional[float] = None,
           activation: Optional[str] = "relu", bias: Optional[float] = 0.0,
           kernel_initializer=None, name: Optional[str] = None):
    """2-D convolution (ref: convnet_builder.py:154-242).

    ``SAME_RESNET`` mode reproduces the v1.5 stride-2 padding: explicit
    (k-1) total padding before a VALID conv (ref: convnet_builder.py:205-223).
    """
    if input_layer is None:
      input_layer = self.top_layer
    name = name or self._name("conv")
    use_bn = self.use_batch_norm if use_batch_norm is None else use_batch_norm
    if kernel_initializer is None:
      if stddev is None:
        # Glorot uniform, the Keras Conv2D default the reference inherits
        # (ref: convnet_builder.py:107-113 keras Conv2D w/o initializer).
        kernel_initializer = nn.initializers.variance_scaling(
            1.0, "fan_avg", "uniform")
      else:
        kernel_initializer = nn.initializers.truncated_normal(stddev=stddev)
    x = self._spatial(jnp.asarray(input_layer, self.dtype))
    if mode == "SAME_RESNET":
      if d_height > 1 or d_width > 1:
        pad_h, pad_w = k_height - 1, k_width - 1
        padding = [(pad_h // 2, pad_h - pad_h // 2),
                   (pad_w // 2, pad_w - pad_w // 2)]
      else:
        padding = "SAME"
    else:
      padding = mode
    x = nn.Conv(
        features=num_out_channels,
        kernel_size=(k_height, k_width),
        strides=(d_height, d_width),
        padding=padding,
        use_bias=(not use_bn and bias is not None),
        bias_init=nn.initializers.constant(bias or 0.0),
        kernel_init=kernel_initializer,
        dtype=self.dtype,
        param_dtype=self.param_dtype,
        name=name)(x)
    x = self._unspatial(x)
    if use_bn:
      x = self._batch_norm_impl(x, name=name + "_bn")
    x = _activate(x, activation)
    self.top_layer = x
    self.top_size = num_out_channels
    return x

  def _pool(self, pool: str, k_height: int, k_width: int, d_height: int,
            d_width: int, mode: str, input_layer, name: Optional[str]):
    if input_layer is None:
      input_layer = self.top_layer
    else:
      # Pooling keeps channel count; re-anchor top_size to the explicit
      # input (ref: convnet_builder.py:215-230 num_channels_in handling).
      self.top_size = int(input_layer.shape[self.channel_axis])
    name = name or self._name(pool)
    x = self._spatial(input_layer)
    window = (1, k_height, k_width, 1)
    strides = (1, d_height, d_width, 1)
    if pool == "mpool":
      init, op = -jnp.inf, jax.lax.max
      x = jax.lax.reduce_window(x, init, op, window, strides, mode)
    else:
      summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides,
                                     mode)
      ones = jnp.ones(x.shape[1:3] + (1,), x.dtype)[None]
      counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides,
                                     mode)
      x = summed / counts
    x = self._unspatial(x)
    self.top_layer = x
    return x

  def mpool(self, k_height, k_width, d_height=2, d_width=2, mode="VALID",
            input_layer=None, num_channels_in=None, name=None):
    """Max pool (ref: convnet_builder.py:243-254)."""
    del num_channels_in  # channel count inferred from the input's shape
    return self._pool("mpool", k_height, k_width, d_height, d_width, mode,
                      input_layer, name)

  def apool(self, k_height, k_width, d_height=2, d_width=2, mode="VALID",
            input_layer=None, num_channels_in=None, name=None):
    """Average pool (ref: convnet_builder.py:256-266)."""
    del num_channels_in
    return self._pool("apool", k_height, k_width, d_height, d_width, mode,
                      input_layer, name)

  def reshape(self, shape, input_layer=None):
    """(ref: convnet_builder.py:268-273)"""
    if input_layer is None:
      input_layer = self.top_layer
    x = jnp.reshape(input_layer, shape)
    self.top_layer = x
    self.top_size = int(x.shape[-1])
    return x

  def affine(self, num_out_channels: int, input_layer=None,
             num_channels_in: Optional[int] = None, bias: float = 0.0,
             stddev: Optional[float] = None, activation: Optional[str] = "relu",
             name: Optional[str] = None):
    """Fully connected layer (ref: convnet_builder.py:311-345)."""
    if input_layer is None:
      input_layer = self.top_layer
    name = name or self._name("affine")
    x = jnp.asarray(input_layer, self.dtype)
    if x.ndim > 2:
      x = jnp.reshape(x, (x.shape[0], -1))
    if stddev is None:
      # He-style fan-in truncated normal, matching the reference's affine
      # default: sqrt(init_factor / num_channels_in), init_factor 2 for
      # relu else 1 (ref: convnet_builder.py affine).
      init_factor = 2.0 if activation == "relu" else 1.0
      stddev = float(init_factor / int(x.shape[-1])) ** 0.5
    kernel_init = nn.initializers.truncated_normal(stddev=stddev)
    x = nn.Dense(features=num_out_channels,
                 kernel_init=kernel_init,
                 bias_init=nn.initializers.constant(bias),
                 dtype=self.dtype,
                 param_dtype=self.param_dtype,
                 name=name)(x)
    x = _activate(x, activation)
    self.top_layer = x
    self.top_size = num_out_channels
    return x

  def inception_module(self, name: str, cols: Sequence[Sequence]):
    """Column-parallel spec interpreter (ref: convnet_builder.py:347-382).

    Each column is a list of (op_name, *args) tuples over ops of this
    builder; column outputs are concatenated on the channel axis. A
    ``('share',)`` entry reuses the previous column's layer at the same
    depth index (enabling split-then-branch structures like Inception
    v3's mixed_9/10 blocks).
    """
    start_layer = self.top_layer
    start_size = self.top_size
    col_layers: list = []
    col_sizes: list = []
    for c, column in enumerate(cols):
      col_layers.append([])
      col_sizes.append([])
      for l, op_spec in enumerate(column):
        op_name, args = op_spec[0], op_spec[1:]
        kwargs = {"input_layer": start_layer} if l == 0 else {}
        if op_name == "share":
          self.top_layer = col_layers[c - 1][l]
          self.top_size = col_sizes[c - 1][l]
        elif op_name in ("conv", "mpool", "apool"):
          getattr(self, op_name)(*args, **kwargs)
        else:
          raise KeyError(
              f"Invalid layer type for inception module: {op_name!r}")
        col_layers[c].append(self.top_layer)
        col_sizes[c].append(self.top_size)
    self.top_layer = jnp.concatenate([layers[-1] for layers in col_layers],
                                     axis=self.channel_axis)
    self.top_size = sum(sizes[-1] for sizes in col_sizes)
    return self.top_layer

  def spatial_mean(self, keep_dims: bool = False, input_layer=None):
    """Global average pool over H,W (ref: convnet_builder.py:385-393)."""
    if input_layer is None:
      input_layer = self.top_layer
    axes = (1, 2) if self.data_format == "NHWC" else (2, 3)
    x = jnp.mean(input_layer, axis=axes, keepdims=keep_dims)
    self.top_layer = x
    return x

  def dropout(self, keep_prob: float = 0.5, input_layer=None):
    """(ref: convnet_builder.py:395-406). Note keep_prob, not rate."""
    if input_layer is None:
      input_layer = self.top_layer
    name = self._name("dropout")
    x = nn.Dropout(rate=1.0 - keep_prob, name=name)(
        input_layer, deterministic=not self.phase_train)
    self.top_layer = x
    return x

  def _batch_norm_impl(self, x, name, decay=None, scale=None, epsilon=None):
    cfg = self.batch_norm_config
    decay = cfg["decay"] if decay is None else decay
    scale = cfg["scale"] if scale is None else scale
    epsilon = cfg["epsilon"] if epsilon is None else epsilon
    x = self._spatial(x)
    x = CompactBatchNorm(
        use_running_average=not self.phase_train,
        momentum=decay,
        epsilon=epsilon,
        use_scale=scale,
        use_bias=True,
        dtype=self.dtype,
        param_dtype=self.param_dtype,
        name=name)(x)
    return self._unspatial(x)

  def batch_norm(self, input_layer=None, decay=None, scale=None,
                 epsilon=None, name=None):
    """Batch normalization (ref: convnet_builder.py:408-462)."""
    if input_layer is None:
      input_layer = self.top_layer
    name = name or self._name("batchnorm")
    x = self._batch_norm_impl(input_layer, name, decay=decay, scale=scale,
                              epsilon=epsilon)
    self.top_layer = x
    return x

  def lrn(self, depth_radius: int, bias: float, alpha: float, beta: float,
          input_layer=None):
    """Local response normalization (ref: convnet_builder.py:463-468).

    Matches tf.nn.lrn semantics: sqr_sum[b,h,w,c] = sum over the
    [c-r, c+r] channel window of squares; out = x / (bias + alpha*sqr_sum)^beta.
    """
    if input_layer is None:
      input_layer = self.top_layer
    x = self._spatial(input_layer)
    squares = jnp.square(x)
    window = 2 * depth_radius + 1
    sqr_sum = jax.lax.reduce_window(
        squares, 0.0, jax.lax.add,
        (1, 1, 1, window), (1, 1, 1, 1),
        [(0, 0), (0, 0), (0, 0), (depth_radius, depth_radius)])
    x = x / jnp.power(bias + alpha * sqr_sum, beta)
    x = self._unspatial(x)
    self.top_layer = x
    return x
