"""Model registry: dataset-keyed name -> constructor maps.

Re-design of the reference registry (ref:
scripts/tf_cnn_benchmarks/models/model_config.py:38-142). The reference
fork's TF2 port trimmed the registry to ResNet only, with the full model
list commented out -- that commented set is the capability list this
registry restores incrementally (SURVEY 2.5).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from kf_benchmarks_tpu.models import alexnet_model
from kf_benchmarks_tpu.models import deepspeech
from kf_benchmarks_tpu.models import densenet_model
from kf_benchmarks_tpu.models import googlenet_model
from kf_benchmarks_tpu.models import inception_model
from kf_benchmarks_tpu.models import lenet_model
from kf_benchmarks_tpu.models import mobilenet_v2
from kf_benchmarks_tpu.models import nasnet_model
from kf_benchmarks_tpu.models import official_ncf_model
from kf_benchmarks_tpu.models import official_resnet_model
from kf_benchmarks_tpu.models import overfeat_model
from kf_benchmarks_tpu.models import resnet_model
from kf_benchmarks_tpu.models import ssd_model
from kf_benchmarks_tpu.models import transformer_lm
from kf_benchmarks_tpu.models import trivial_model
from kf_benchmarks_tpu.models import vgg_model

_model_name_to_imagenet_model: Dict[str, Callable] = {
    "vgg11": vgg_model.Vgg11Model,
    "vgg16": vgg_model.Vgg16Model,
    "vgg19": vgg_model.Vgg19Model,
    "lenet": lenet_model.Lenet5Model,
    "googlenet": googlenet_model.GooglenetModel,
    "overfeat": overfeat_model.OverfeatModel,
    "alexnet": alexnet_model.AlexnetModel,
    "trivial": trivial_model.TrivialModel,
    "inception3": inception_model.Inceptionv3Model,
    "inception4": inception_model.Inceptionv4Model,
    "mobilenet": mobilenet_v2.create_mobilenet_model,
    "nasnet": nasnet_model.create_nasnet_model,
    "nasnetlarge": nasnet_model.create_nasnetlarge_model,
    "ncf": official_ncf_model.create_ncf_model,
    "transformer_lm": transformer_lm.create_transformer_lm_model,
    "resnet50": resnet_model.create_resnet50_model,
    "resnet50_v1.5": resnet_model.create_resnet50_v15_model,
    "resnet50_v2": resnet_model.create_resnet50_v2_model,
    "resnet101": resnet_model.create_resnet101_model,
    "resnet101_v2": resnet_model.create_resnet101_v2_model,
    "resnet152": resnet_model.create_resnet152_model,
    "resnet152_v2": resnet_model.create_resnet152_v2_model,
    "official_resnet18": official_resnet_model.create_official_resnet18_model,
    "official_resnet34": official_resnet_model.create_official_resnet34_model,
    "official_resnet50": official_resnet_model.create_official_resnet50_model,
    "official_resnet50_v2":
        official_resnet_model.create_official_resnet50_v2_model,
    "official_resnet101":
        official_resnet_model.create_official_resnet101_model,
    "official_resnet152":
        official_resnet_model.create_official_resnet152_model,
    "official_resnet200":
        official_resnet_model.create_official_resnet200_model,
}

_model_name_to_cifar_model: Dict[str, Callable] = {
    "alexnet": alexnet_model.AlexnetCifar10Model,
    "trivial": trivial_model.TrivialCifar10Model,
    "densenet40_k12": densenet_model.create_densenet40_k12_model,
    "densenet100_k12": densenet_model.create_densenet100_k12_model,
    "densenet100_k24": densenet_model.create_densenet100_k24_model,
    "nasnet": nasnet_model.create_nasnet_cifar_model,
    "resnet20": resnet_model.create_resnet20_cifar_model,
    "resnet20_v2": resnet_model.create_resnet20_v2_cifar_model,
    "resnet32": resnet_model.create_resnet32_cifar_model,
    "resnet32_v2": resnet_model.create_resnet32_v2_cifar_model,
    "resnet44": resnet_model.create_resnet44_cifar_model,
    "resnet44_v2": resnet_model.create_resnet44_v2_cifar_model,
    "resnet56": resnet_model.create_resnet56_cifar_model,
    "resnet56_v2": resnet_model.create_resnet56_v2_cifar_model,
    "resnet110": resnet_model.create_resnet110_cifar_model,
    "resnet110_v2": resnet_model.create_resnet110_v2_cifar_model,
}


_model_name_to_object_detection_model: Dict[str, Callable] = {
    "ssd300": ssd_model.create_ssd300_model,
}

_model_name_to_speech_model: Dict[str, Callable] = {
    "deepspeech2": deepspeech.create_deepspeech2_model,
}


def _get_model_map(dataset_name: Optional[str]) -> Dict[str, Callable]:
  """(ref: models/model_config.py:113-124)"""
  if dataset_name == "cifar10":
    return _model_name_to_cifar_model
  if dataset_name == "coco":
    return _model_name_to_object_detection_model
  if dataset_name == "librispeech":
    return _model_name_to_speech_model
  if dataset_name in ("imagenet", "synthetic", None):
    return _model_name_to_imagenet_model
  raise ValueError(f"Invalid dataset name: {dataset_name}")


def get_model_config(model_name: str, dataset_name: Optional[str] = None,
                     params=None):
  """Map model name + dataset to a Model instance (ref :126-133)."""
  model_map = _get_model_map(dataset_name)
  if model_name not in model_map:
    raise ValueError(
        f"Invalid model name '{model_name}' for dataset '{dataset_name}'")
  return model_map[model_name](params=params)


def register_model(model_name: str, dataset_name: str,
                   model_func: Callable) -> None:
  """Register a new model that can be obtained with get_model_config
  (ref :136-142)."""
  model_map = _get_model_map(dataset_name)
  if model_name in model_map:
    raise ValueError(f"Model '{model_name}' already registered for "
                     f"dataset '{dataset_name}'")
  model_map[model_name] = model_func


def list_models(dataset_name: Optional[str] = None):
  return sorted(_get_model_map(dataset_name).keys())
