"""NCF (NeuMF) recommendation model, TPU-native flax implementation.

The reference wraps the official TF NeuMF (ref: scripts/tf_cnn_benchmarks/
models/experimental/official_ncf_model.py:45-129, importing
official.recommendation.neumf_model with ml-20m hyperparameters); here
the NeuMF architecture itself (He et al., "Neural Collaborative
Filtering", arXiv:1708.05031) is implemented natively: a GMF branch
(elementwise product of 64-d embeddings) and an MLP branch
((256, 256, 128, 64) tower over concatenated 128-d embeddings), fused by
a final 1-logit dense layer.

The (user, item) id pair rides the feature slot as an int32 [batch, 2]
array; embedding lookups are dense gathers, which XLA handles natively
(the reference's sparse-grad caveat and --sparse_to_dense_grads flag
disappear: gradients of ``take`` are scatter-adds the compiler fuses).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
import flax.linen as nn

from kf_benchmarks_tpu.models import model as model_lib

_NUM_USERS_20M = 138493
_NUM_ITEMS_20M = 26744


class _NeuMFModule(nn.Module):
  num_users: int = _NUM_USERS_20M
  num_items: int = _NUM_ITEMS_20M
  mf_dim: int = 64
  model_layers: Tuple[int, ...] = (256, 256, 128, 64)
  dtype: Any = jnp.float32
  param_dtype: Any = jnp.float32

  @nn.compact
  def __call__(self, user_item):
    ids = user_item.astype(jnp.int32)
    users, items = ids[:, 0], ids[:, 1]
    embed = lambda n, d, name: nn.Embed(
        n, d, name=name, dtype=self.dtype, param_dtype=self.param_dtype)
    # GMF branch
    mf_u = embed(self.num_users, self.mf_dim, "mf_user_embedding")(users)
    mf_i = embed(self.num_items, self.mf_dim, "mf_item_embedding")(items)
    gmf = mf_u * mf_i
    # MLP branch (embedding dim = first layer / 2 each, as in the
    # official neumf construction)
    mlp_dim = self.model_layers[0] // 2
    mlp_u = embed(self.num_users, mlp_dim, "mlp_user_embedding")(users)
    mlp_i = embed(self.num_items, mlp_dim, "mlp_item_embedding")(items)
    x = jnp.concatenate([mlp_u, mlp_i], axis=-1)
    for width in self.model_layers[1:]:
      x = nn.relu(nn.Dense(width, dtype=self.dtype,
                           param_dtype=self.param_dtype)(x))
    fused = jnp.concatenate([gmf, x], axis=-1)
    logits = nn.Dense(1, dtype=self.dtype,
                      param_dtype=self.param_dtype)(fused)
    return logits.astype(jnp.float32), None


class NcfModel(model_lib.Model):
  """(ref: official_ncf_model.py:45-129)."""

  def __init__(self, params=None):
    super().__init__("official_ncf", batch_size=2048, learning_rate=0.0005,
                     fp16_loss_scale=128, params=params)

  def make_module(self, nclass, phase_train, data_format="NHWC",
                  dtype=jnp.float32, param_dtype=jnp.float32):
    del nclass, phase_train, data_format
    return _NeuMFModule(dtype=dtype, param_dtype=param_dtype)

  def get_input_shapes(self, subset):
    n = self.get_batch_size()
    return [[n, 2], [n]]

  def get_input_data_types(self, subset):
    return [jnp.int32, jnp.int32]

  def get_synthetic_inputs(self, rng, nclass):
    n = self.get_batch_size()
    r_u, r_i, r_l = jax.random.split(rng, 3)
    users = jax.random.randint(r_u, (n,), 0, _NUM_USERS_20M, jnp.int32)
    items = jax.random.randint(r_i, (n,), 0, _NUM_ITEMS_20M, jnp.int32)
    labels = jax.random.randint(r_l, (n,), 0, 2, jnp.int32)
    return jnp.stack([users, items], axis=1), labels

  def loss_function(self, build_network_result, labels):
    """Sigmoid cross-entropy, expressed as the reference does: softmax
    against a ones column (ref :85-98, quirk kept for parity)."""
    logits, _ = build_network_result.logits
    two_col = jnp.concatenate([jnp.ones_like(logits), logits], axis=1)
    onehot = jax.nn.one_hot(labels, 2, dtype=two_col.dtype)
    return jnp.mean(-jnp.sum(
        onehot * jax.nn.log_softmax(two_col), axis=-1))

  def accuracy_function(self, build_network_result, labels):
    logits, _ = build_network_result.logits
    pred = (logits[:, 0] > 1.0).astype(jnp.int32)  # vs the ones column
    acc = jnp.mean((pred == labels).astype(jnp.float32))
    return {"top_1_accuracy": acc, "top_5_accuracy": jnp.ones(())}


def create_ncf_model(params=None):
  return NcfModel(params=params)
