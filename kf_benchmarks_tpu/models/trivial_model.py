"""Trivial MLP smoke-test model (ref: models/trivial_model.py:20-43)."""

from kf_benchmarks_tpu.models import model


class TrivialModel(model.CNNModel):
  """Flatten -> 1-unit bottleneck -> 4096 hidden, as in the reference."""

  def __init__(self, params=None):
    super().__init__("trivial", 224 + 3, 32, 0.005, params=params)

  def add_inference(self, cnn):
    cnn.reshape([-1, 227 * 227 * 3])
    cnn.affine(1)
    cnn.affine(4096)


class TrivialCifar10Model(model.CNNModel):
  """Cifar-sized trivial model (ref: models/trivial_model.py:33-43)."""

  def __init__(self, params=None):
    super().__init__("trivial", 32, 32, 0.005, params=params)

  def add_inference(self, cnn):
    cnn.reshape([-1, 32 * 32 * 3])
    cnn.affine(1)
    cnn.affine(4096)
